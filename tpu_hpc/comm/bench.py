"""Collective micro-benchmark over ICI/DCN: the ``torch_comm_bench`` port.

Parity with /root/reference/tests/torch_comm_bench.py:
  * broadcast + all-reduce (plus TPU extras: all-gather, reduce-scatter,
    ring send/recv) across element counts 10^3..10^8  (:196-240)
  * N warmup + M timed iterations, barrier-bracketed   (:40-90)
  * ring bus-bandwidth accounting 2(n-1)/n * size / t  (:92-116)
  * CSV output with a full environment-metadata header (:137-194)
  * CLI flags for sizes/warmup/bench/output             (:253-267)

The "barrier" on TPU is ``block_until_ready`` on the input (ensures
async dispatch has drained) before starting the clock, and on the
output before stopping it -- the same wall-clock bracketing as the
reference's ``dist.barrier(); t0; op; synchronize; barrier; t1``.

Caveat for tunneled/remote dev backends (not real pods): some proxy
transports complete ``block_until_ready`` before device execution
finishes, which inflates rates. On such backends trust the marginal
-rate microbench (checks/env_check.py:chip_microbench) and the
trainer's device_get-bracketed throughput instead; on a real TPU-VM
the bracketing here behaves like the reference's.
"""
from __future__ import annotations

import csv
import dataclasses
import io
import socket
import time
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpu_hpc.comm import primitives

DEFAULT_SIZES = tuple(10**k for k in range(3, 9))  # torch_comm_bench.py:174
OPS = (
    "broadcast", "all_reduce", "all_gather", "reduce_scatter",
    "ring_shift", "all_to_all",
)


def bus_bandwidth_gb_s(op: str, bytes_per_shard: int, n: int, t: float) -> float:
    """Ring bus-bandwidth model, matching torch_comm_bench.py:92-116.

    broadcast: size/t. all-reduce: 2(n-1)/n * size/t. all-gather and
    reduce-scatter move (n-1)/n * size: the standard NCCL-tests busbw
    factors, applied unchanged to ICI.
    """
    if t <= 0:
        return float("inf")
    factor = {
        "broadcast": 1.0,
        "all_reduce": 2.0 * (n - 1) / n,
        "all_gather": (n - 1) / n,
        "reduce_scatter": (n - 1) / n,
        "ring_shift": 1.0,
        "all_to_all": (n - 1) / n,
    }[op]
    return factor * bytes_per_shard / t / 1e9


@dataclasses.dataclass
class CommBenchmark:
    """Configurable collective benchmark over one mesh axis."""

    mesh: Mesh
    axis: str = "data"
    sizes: Sequence[int] = DEFAULT_SIZES
    warmup: int = 5  # torch_comm_bench default :255
    iters: int = 20  # :256
    ops: Sequence[str] = OPS
    dtype: str = "float32"

    def _input_for(self, op: str, n_elements: int):
        """Build the benchmark payload. ``n_elements`` is the per-shard
        element count (matching the reference, where every rank holds
        `size` elements)."""
        n = self.mesh.shape[self.axis]
        dt = jnp.dtype(self.dtype)
        if op in ("broadcast", "all_reduce", "all_gather", "ring_shift"):
            # globally [n*size], sharded over axis: each device holds `size`.
            x = jnp.arange(n * n_elements, dtype=dt)
            return jax.device_put(x, NamedSharding(self.mesh, P(self.axis)))
        elif op == "reduce_scatter":
            # replicated [n*size] input; output sharded.
            x = jnp.arange(n * n_elements, dtype=dt)
            return jax.device_put(x, NamedSharding(self.mesh, P()))
        elif op == "all_to_all":
            # The Ulysses building block: [n, inner] sharded on dim 0
            # in, dim 1 out; each device still holds ~``size`` elements
            # (inner rounded up so the n-way column split is exact).
            inner = -(-n_elements // n) * n
            x = jnp.arange(n * inner, dtype=dt).reshape(n, inner)
            return jax.device_put(x, NamedSharding(self.mesh, P(self.axis)))
        raise ValueError(op)

    def run(self) -> List[Dict]:
        n = self.mesh.shape[self.axis]
        records = []
        for op in self.ops:
            fn = getattr(primitives, op)(self.mesh, self.axis)
            for size in self.sizes:
                x = self._input_for(op, size)
                x.block_until_ready()
                for _ in range(self.warmup):
                    fn(x).block_until_ready()
                times = []
                for _ in range(self.iters):
                    x.block_until_ready()  # barrier (ref :44-46)
                    t0 = time.perf_counter()
                    out = fn(x)
                    out.block_until_ready()  # synchronize (ref :52-56)
                    times.append(time.perf_counter() - t0)
                times = np.asarray(times)
                # Per-shard payload from the actual array (all_to_all
                # rounds the element count up to an n-divisible size).
                nbytes = x.nbytes // n
                rec = {
                    "op": op,
                    "size_elements": size,
                    "bytes_per_shard": nbytes,
                    "world_size": n,
                    "mean_s": float(times.mean()),
                    "std_s": float(times.std()),
                    "min_s": float(times.min()),
                    "max_s": float(times.max()),
                    "busbw_GB_s": bus_bandwidth_gb_s(
                        op, nbytes, n, float(times.mean())
                    ),
                }
                records.append(rec)
        return records


def _env_metadata(mesh: Mesh) -> Dict[str, str]:
    """CSV metadata header block, parity with torch_comm_bench.py:153-194
    (host, versions, backend, world size -> TPU equivalents)."""
    d = jax.devices()[0]
    return {
        "hostname": socket.gethostname(),
        "jax_version": jax.__version__,
        "backend": jax.default_backend(),
        "device_kind": d.device_kind,
        "process_count": str(jax.process_count()),
        "global_devices": str(jax.device_count()),
        "mesh": str(dict(mesh.shape)),
        "timestamp": time.strftime("%Y-%m-%d %H:%M:%S"),
    }


def write_csv(records: List[Dict], mesh: Mesh, path: Optional[str]) -> str:
    """Write benchmark CSV (metadata as comment lines, then rows).
    Returns the CSV text. Rank-0-only output is implicit: call from
    host 0 (jax arrays are process-global)."""
    buf = io.StringIO()
    for k, v in _env_metadata(mesh).items():
        buf.write(f"# {k}: {v}\n")
    if records:
        w = csv.DictWriter(buf, fieldnames=list(records[0].keys()))
        w.writeheader()
        w.writerows(records)
    text = buf.getvalue()
    if path and jax.process_index() == 0:
        with open(path, "w") as f:
            f.write(text)
    return text


def run_comm_bench(
    mesh: Optional[Mesh] = None,
    axis: str = "data",
    sizes: Sequence[int] = DEFAULT_SIZES,
    warmup: int = 5,
    iters: int = 20,
    ops: Sequence[str] = OPS,
    output: Optional[str] = None,
) -> List[Dict]:
    """One-call benchmark entry (the ``init_processes`` analogue,
    torch_comm_bench.py:144-251)."""
    if mesh is None:
        from tpu_hpc.runtime import MeshSpec, build_mesh

        mesh = build_mesh(MeshSpec(axes={axis: -1}))
    bench = CommBenchmark(
        mesh=mesh, axis=axis, sizes=sizes, warmup=warmup, iters=iters, ops=ops
    )
    records = bench.run()
    text = write_csv(records, mesh, output)
    if jax.process_index() == 0 and output is None:
        print(text)
    return records


def main(argv: Optional[Sequence[str]] = None) -> None:
    import argparse

    p = argparse.ArgumentParser(description="XLA collective benchmark over ICI")
    p.add_argument("--sizes", type=int, nargs="+", default=list(DEFAULT_SIZES))
    p.add_argument("--warmup", type=int, default=5)
    p.add_argument("--iters", type=int, default=20)
    p.add_argument("--ops", nargs="+", default=list(OPS), choices=OPS)
    p.add_argument("--output", type=str, default=None)
    p.add_argument("--axis-size", type=int, default=-1)
    args = p.parse_args(argv)

    from tpu_hpc.runtime import MeshSpec, build_mesh, init_distributed

    init_distributed()
    mesh = build_mesh(MeshSpec(axes={"data": args.axis_size}))
    run_comm_bench(
        mesh,
        sizes=args.sizes,
        warmup=args.warmup,
        iters=args.iters,
        ops=args.ops,
        output=args.output,
    )


if __name__ == "__main__":
    main()
