"""Collective micro-benchmark over ICI/DCN: the ``torch_comm_bench`` port.

Parity with /root/reference/tests/torch_comm_bench.py:
  * broadcast + all-reduce (plus TPU extras: all-gather, reduce-scatter,
    ring send/recv) across element counts 10^3..10^8  (:196-240)
  * N warmup + M timed iterations, barrier-bracketed   (:40-90)
  * ring bus-bandwidth accounting 2(n-1)/n * size / t  (:92-116)
  * CSV output with a full environment-metadata header (:137-194)
  * CLI flags for sizes/warmup/bench/output             (:253-267)

Beyond the port, the comm-performance layer's ops are benched too:
  * hierarchical (two-phase) all-reduce / all-gather / reduce-scatter
    over a (dcn x ici) mesh (comm.hierarchical), with two-phase
    bus-bandwidth accounting: each record carries the per-device wire
    bytes of the ICI and DCN phases separately, because the whole
    point of the decomposition is that the DCN share shrinks by
    ~n_ici while the flat op ships the full payload cross-slice.
  * the overlap building blocks (comm.overlap): the ppermute ring
    all-gather and the collective-matmul-style gather_matmul (whose
    time includes the overlapped partial matmuls -- its busbw row is
    a lower bound on the gather throughput, by design).

Records land as CSV (metadata header + rows) AND JSONL (one record
per line, the BENCH-artifact format) when an output path is given.

The "barrier" on TPU is ``block_until_ready`` on the input (ensures
async dispatch has drained) before starting the clock, and on the
output before stopping it -- the same wall-clock bracketing as the
reference's ``dist.barrier(); t0; op; synchronize; barrier; t1``.

Caveat for tunneled/remote dev backends (not real pods): some proxy
transports complete ``block_until_ready`` before device execution
finishes, which inflates rates. On such backends trust the marginal
-rate microbench (checks/env_check.py:chip_microbench) and the
trainer's device_get-bracketed throughput instead; on a real TPU-VM
the bracketing here behaves like the reference's.
"""
from __future__ import annotations

import csv
import dataclasses
import io
import json
import os
import socket
import sys
import time
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpu_hpc.comm import hierarchical, overlap, primitives

DEFAULT_SIZES = tuple(10**k for k in range(3, 9))  # torch_comm_bench.py:174
OPS = (
    "broadcast", "all_reduce", "all_gather", "reduce_scatter",
    "ring_shift", "all_to_all",
)
# Two-phase decompositions: need a (dcn x ici) mesh (comm.hierarchical).
HIER_OPS = ("hier_all_reduce", "hier_all_gather", "hier_reduce_scatter")
# Comm/compute-overlap building blocks (comm.overlap); run on the flat
# axis like the classic ops.
OVERLAP_OPS = ("ppermute_all_gather", "gather_matmul")
# Reshard-engine ops (tpu_hpc.reshard): plan + execute timings with
# modeled vs. measured bytes; each has a ``_bounded`` flavor running
# the chunked decomposition under max_inflight_bytes = total/4.
RESHARD_OPS = ("reshard_exchange", "reshard_replicate")
ALL_OPS = OPS + HIER_OPS + OVERLAP_OPS + RESHARD_OPS

# gather_matmul's fixed output width: the benched payload is the
# sharded weight [K/n, N]; K scales with the requested element count.
_GM_COLS = 128
_GM_ROWS_PER_SHARD = 8

# busbw factor class of each op (NCCL-tests convention, applied to the
# per-shard payload): the hierarchical/overlap ops reuse their flat
# op's factor so their rows are directly comparable to the flat rows.
_BUSBW_BASE = {
    "broadcast": "broadcast",
    "all_reduce": "all_reduce",
    "all_gather": "all_gather",
    "reduce_scatter": "reduce_scatter",
    "ring_shift": "ring_shift",
    "all_to_all": "all_to_all",
    "hier_all_reduce": "all_reduce",
    "hier_all_gather": "all_gather",
    "hier_reduce_scatter": "reduce_scatter",
    "ppermute_all_gather": "all_gather",
    "gather_matmul": "all_gather",
}


def wire_factor(op: str, n: int) -> float:
    """Per-device wire share of one flat collective over an ``n``-wide
    axis (the NCCL-tests busbw factor table) -- THE one copy: the CSV
    rows' busbw accounting and the planner's analytic cost model
    (comm/planner.py) both read it, so a factor correction can never
    leave the two computing from different wire models."""
    return {
        "broadcast": 1.0,
        "all_reduce": 2.0 * (n - 1) / n,
        "all_gather": (n - 1) / n,
        "reduce_scatter": (n - 1) / n,
        "ring_shift": 1.0,
        "all_to_all": (n - 1) / n,
    }[op]


def bus_bandwidth_gb_s(op: str, bytes_per_shard: int, n: int, t: float) -> float:
    """Ring bus-bandwidth model, matching torch_comm_bench.py:92-116.

    broadcast: size/t. all-reduce: 2(n-1)/n * size/t. all-gather and
    reduce-scatter move (n-1)/n * size: the standard NCCL-tests busbw
    factors (:func:`wire_factor`), applied unchanged to ICI.
    Hierarchical/overlap ops use their flat op's factor over the TOTAL
    axis extent (comparability with the flat row; the phase split is
    reported separately by :func:`two_phase_bytes`).
    """
    if t <= 0:
        return float("inf")
    return wire_factor(_BUSBW_BASE[op], n) * bytes_per_shard / t / 1e9


def two_phase_bytes(
    op: str, bytes_per_shard: int, n_dcn: int, n_ici: int
) -> Tuple[float, float]:
    """Per-device wire bytes of each phase of a hierarchical op:
    ``(ici_bytes, dcn_bytes)``.

    S = per-shard payload bytes; the decompositions are in
    comm.hierarchical. The headline number is the DCN column: the
    flat op ships its FULL cross-slice share over DCN, the two-phase
    op only the 1/n_ici-reduced shard (all-reduce) or exactly one
    copy of each remote shard (all-gather).

      hier_all_reduce:     ICI 2*S*(n_ici-1)/n_ici   (RS + AG on S)
                           DCN 2*(S/n_ici)*(n_dcn-1)/n_dcn
      hier_all_gather:     ICI S*n_dcn*(n_ici-1)     (redistribute)
                           DCN S*(n_dcn-1)           (one copy each)
      hier_reduce_scatter: ICI n*S*(n_ici-1)/n_ici   (scatter on n*S)
                           DCN S*(n_dcn-1)           (1/n_ici chunk)
    """
    s = float(bytes_per_shard)
    if op == "hier_all_reduce":
        return (
            2.0 * s * (n_ici - 1) / n_ici,
            2.0 * (s / n_ici) * (n_dcn - 1) / n_dcn,
        )
    if op == "hier_all_gather":
        return s * n_dcn * (n_ici - 1), s * (n_dcn - 1)
    if op == "hier_reduce_scatter":
        n = n_dcn * n_ici
        return n * s * (n_ici - 1) / n_ici, s * (n_dcn - 1)
    raise ValueError(f"not a two-phase op: {op}")


@dataclasses.dataclass
class CommBenchmark:
    """Configurable collective benchmark over one mesh axis (flat and
    overlap ops) or a (dcn x ici) axis pair (hierarchical ops, with
    ``dcn_axis`` naming the outer tier)."""

    mesh: Mesh
    axis: str = "data"
    sizes: Sequence[int] = DEFAULT_SIZES
    warmup: int = 5  # torch_comm_bench default :255
    iters: int = 20  # :256
    ops: Sequence[str] = OPS
    dtype: str = "float32"
    dcn_axis: Optional[str] = None

    def _world(self, op: str) -> int:
        n = self.mesh.shape[self.axis]
        if op in HIER_OPS:
            return n * self.mesh.shape[self.dcn_axis]
        return n

    def _fn_for(self, op: str):
        if op in HIER_OPS:
            if self.dcn_axis is None:
                raise ValueError(
                    f"{op} needs dcn_axis= (a two-tier mesh); got a "
                    "flat single-axis benchmark"
                )
            return getattr(hierarchical, op)(
                self.mesh, self.dcn_axis, self.axis
            )
        if op == "ppermute_all_gather":
            return overlap.ppermute_all_gather(self.mesh, self.axis)
        if op == "gather_matmul":
            return overlap.make_pipelined_gather_matmul(self.mesh, self.axis)
        return getattr(primitives, op)(self.mesh, self.axis)

    def _input_for(self, op: str, n_elements: int):
        """Build the benchmark payload: ``(args, bytes_per_shard)``.
        ``n_elements`` is the per-shard element count (matching the
        reference, where every rank holds `size` elements)."""
        n = self._world(op)
        dt = jnp.dtype(self.dtype)
        data_spec = (
            P((self.dcn_axis, self.axis)) if op in HIER_OPS
            else P(self.axis)
        )
        if op in (
            "broadcast", "all_reduce", "all_gather", "ring_shift",
            "hier_all_reduce", "hier_all_gather", "ppermute_all_gather",
        ):
            # globally [n*size], sharded over the axis (pair): each
            # device holds `size`.
            x = jnp.arange(n * n_elements, dtype=dt)
            x = jax.device_put(x, NamedSharding(self.mesh, data_spec))
            return (x,), x.nbytes // n
        elif op in ("reduce_scatter", "hier_reduce_scatter"):
            # replicated [n*size] input; output sharded.
            x = jnp.arange(n * n_elements, dtype=dt)
            x = jax.device_put(x, NamedSharding(self.mesh, P()))
            return (x,), x.nbytes // n
        elif op == "all_to_all":
            # The Ulysses building block: [n, inner] sharded on dim 0
            # in, dim 1 out; each device still holds ~``size`` elements
            # (inner rounded up so the n-way column split is exact).
            inner = -(-n_elements // n) * n
            x = jnp.arange(n * inner, dtype=dt).reshape(n, inner)
            x = jax.device_put(x, NamedSharding(self.mesh, P(self.axis)))
            return (x,), x.nbytes // n
        elif op == "gather_matmul":
            # FSDP forward shape: x batch-sharded [n*rows, K], weight
            # dim-0-sharded [K, cols]; the benched payload is the
            # weight shard (what the ring gathers).
            k_shard = max(-(-n_elements // _GM_COLS), 1)
            k = n * k_shard
            w = jnp.arange(k * _GM_COLS, dtype=dt).reshape(k, _GM_COLS)
            x = jnp.ones((n * _GM_ROWS_PER_SHARD, k), dtype=dt)
            w = jax.device_put(w, NamedSharding(self.mesh, P(self.axis)))
            x = jax.device_put(x, NamedSharding(self.mesh, P(self.axis)))
            return (x, w), w.nbytes // n
        raise ValueError(op)

    def run(self) -> List[Dict]:
        from tpu_hpc.comm.planner import fingerprint_mesh

        # Topology fingerprint: the planner's cost-table cache key.
        # Deliberately a function of the DEVICE SET (not this mesh's
        # axis layout), so the flat and hierarchical rows of one sweep
        # key the same table (comm/planner.py).
        fp = fingerprint_mesh(self.mesh).digest
        records = []
        for op in self.ops:
            fn = self._fn_for(op)
            n = self._world(op)
            for size in self.sizes:
                args, nbytes = self._input_for(op, size)
                for a in args:
                    a.block_until_ready()
                for _ in range(self.warmup):
                    fn(*args).block_until_ready()
                times = []
                for _ in range(self.iters):
                    for a in args:  # barrier (ref :44-46)
                        a.block_until_ready()
                    t0 = time.perf_counter()
                    out = fn(*args)
                    out.block_until_ready()  # synchronize (ref :52-56)
                    times.append(time.perf_counter() - t0)
                times = np.asarray(times)
                rec = {
                    "op": op,
                    "size_elements": size,
                    "bytes_per_shard": nbytes,
                    "dtype": self.dtype,
                    "fingerprint": fp,
                    "world_size": n,
                    "mean_s": float(times.mean()),
                    "std_s": float(times.std()),
                    "min_s": float(times.min()),
                    "max_s": float(times.max()),
                    "busbw_GB_s": bus_bandwidth_gb_s(
                        op, nbytes, n, float(times.mean())
                    ),
                }
                if op in HIER_OPS:
                    n_dcn = self.mesh.shape[self.dcn_axis]
                    n_ici = self.mesh.shape[self.axis]
                    ici_b, dcn_b = two_phase_bytes(
                        op, nbytes, n_dcn, n_ici
                    )
                    rec.update({
                        "n_dcn": n_dcn,
                        "n_ici": n_ici,
                        "ici_bytes_per_shard": round(ici_b),
                        "dcn_bytes_per_shard": round(dcn_b),
                        "dcn_fraction": round(
                            dcn_b / (dcn_b + ici_b), 6
                        ) if (dcn_b + ici_b) else 0.0,
                    })
                records.append(rec)
        return records


def run_reshard_bench(
    mesh: Mesh,
    axis: str = "data",
    sizes: Sequence[int] = DEFAULT_SIZES,
    warmup: int = 5,
    iters: int = 20,
    ops: Sequence[str] = RESHARD_OPS,
    dtype: str = "float32",
) -> List[Dict]:
    """Benchmark the reshard engine's plan + execute over one mesh
    axis, emitting SCHEMA-STAMPED bench rows (obs.schema ``bench``
    events) so the rows ride straight into the regress gate's --bank
    diff next to the training/serving history.

    Two ops x two flavors per size:

    * ``reshard_exchange``  -- ``[n, inner]`` sharded dim 0 -> dim 1
      (the Ulysses-style axis swap, GSPMD's full-remat trap);
    * ``reshard_replicate`` -- sharded -> fully replicated (the
      required-residency case; never bounded, the full copy IS the
      target);
    * ``*_bounded``         -- the same exchange decomposed under
      ``max_inflight_bytes = total_bytes / 4``: what the bound costs
      in time is exactly what it saves in peak HBM, and both sides of
      that trade land in one row (``plan_ms``, ``mean_s``,
      ``wire_bytes_modeled`` vs ``bytes_moved``, ``chunks``,
      ``peak_inflight_bytes``).
    """
    from tpu_hpc import reshard
    from tpu_hpc.comm.planner import fingerprint_mesh
    from tpu_hpc.obs.schema import stamp

    fp = fingerprint_mesh(mesh).digest
    n = mesh.shape[axis]
    if n < 2:
        print(
            f"comm.bench: skipping reshard ops -- axis {axis!r} has "
            f"size {n} (< 2): nothing to redistribute",
            file=sys.stderr,
        )
        return []
    dt = jnp.dtype(dtype)
    records: List[Dict] = []
    for op in ops:
        if op not in RESHARD_OPS:
            raise ValueError(f"not a reshard op: {op}")
        flavors = (
            (False, True) if op == "reshard_exchange" else (False,)
        )
        for bounded in flavors:
            for size in sizes:
                if op == "reshard_exchange":
                    inner = -(-size // n) * n
                    x = jnp.arange(n * inner, dtype=dt).reshape(
                        n, inner
                    )
                    src, tgt = P(axis), P(None, axis)
                else:
                    x = jnp.arange(n * size, dtype=dt)
                    src, tgt = P(axis), P()
                x = jax.device_put(x, NamedSharding(mesh, src))
                x.block_until_ready()
                bound = x.nbytes // 4 if bounded else None
                t0 = time.perf_counter()
                plan = reshard.plan_reshard(
                    {"x": x}, {"x": NamedSharding(mesh, tgt)},
                    max_inflight_bytes=bound,
                )
                plan_ms = (time.perf_counter() - t0) * 1e3
                for _ in range(warmup):
                    plan.execute({"x": x})["x"].block_until_ready()
                times = []
                for _ in range(iters):
                    x.block_until_ready()
                    t0 = time.perf_counter()
                    out = plan.execute({"x": x})
                    out["x"].block_until_ready()
                    times.append(time.perf_counter() - t0)
                times = np.asarray(times)
                mean = float(times.mean())
                name = op + ("_bounded" if bounded else "")
                step = plan.steps[0]
                common = {
                    "op": name,
                    "size_elements": size,
                    "bytes_per_shard": x.nbytes // n,
                    "dtype": dtype,
                    "fingerprint": fp,
                    "world_size": n,
                    "max_inflight_bytes": bound,
                }
                # The size rides IN the metric name: the bank gate
                # reduces per metric (best on the baseline side,
                # latest on the candidate side), and a sweep emitting
                # one name for every size would diff
                # min-across-sizes against the last size measured.
                records.append(stamp({
                    "event": "bench",
                    "metric": f"{name}_n{size}_ms",
                    "value": round(mean * 1e3, 6),
                    "unit": "ms",
                    **common,
                    "mean_s": mean,
                    "std_s": float(times.std()),
                    "min_s": float(times.min()),
                    "max_s": float(times.max()),
                    "plan_ms": round(plan_ms, 6),
                    "wire_bytes_modeled": plan.wire_bytes,
                    "bytes_moved": plan.bytes,
                    "peak_inflight_bytes": plan.peak_inflight_bytes,
                    "chunks": (
                        step.chunk.count if step.chunk else 1
                    ),
                    "busbw_GB_s": (
                        plan.wire_bytes / mean / 1e9 if mean > 0
                        else float("inf")
                    ),
                }))
                records.append(stamp({
                    "event": "bench",
                    "metric": f"{name}_n{size}_wire_bytes",
                    "value": plan.wire_bytes,
                    "unit": "bytes",
                    **common,
                }))
    return records


def _env_metadata(mesh: Mesh) -> Dict[str, str]:
    """CSV metadata header block, parity with torch_comm_bench.py:153-194
    (host, versions, backend, world size -> TPU equivalents)."""
    d = jax.devices()[0]
    return {
        "hostname": socket.gethostname(),
        "jax_version": jax.__version__,
        "backend": jax.default_backend(),
        "device_kind": d.device_kind,
        "process_count": str(jax.process_count()),
        "global_devices": str(jax.device_count()),
        "mesh": str(dict(mesh.shape)),
        "timestamp": time.strftime("%Y-%m-%d %H:%M:%S"),
    }


def _fieldnames(records: List[Dict]) -> List[str]:
    """Union of record keys in first-seen order: hierarchical records
    carry phase columns the flat rows lack, and DictWriter must see
    one superset schema (missing cells stay empty)."""
    names: List[str] = []
    for r in records:
        for k in r:
            if k not in names:
                names.append(k)
    return names


def write_csv(records: List[Dict], mesh: Mesh, path: Optional[str]) -> str:
    """Write benchmark CSV (metadata as comment lines, then rows).
    Returns the CSV text. Rank-0-only output is implicit: call from
    host 0 (jax arrays are process-global)."""
    buf = io.StringIO()
    for k, v in _env_metadata(mesh).items():
        buf.write(f"# {k}: {v}\n")
    if records:
        w = csv.DictWriter(buf, fieldnames=_fieldnames(records))
        w.writeheader()
        w.writerows(records)
    text = buf.getvalue()
    if path and jax.process_index() == 0:
        with open(path, "w") as f:
            f.write(text)
    return text


def write_jsonl(records: List[Dict], path: str) -> None:
    """One JSON record per line -- the BENCH-artifact format, so comm
    rows can ride next to training/serving rows in the same tooling."""
    if jax.process_index() != 0:
        return
    with open(path, "w") as f:
        for r in records:
            f.write(json.dumps(r) + "\n")


def run_comm_bench(
    mesh: Optional[Mesh] = None,
    axis: str = "data",
    sizes: Sequence[int] = DEFAULT_SIZES,
    warmup: int = 5,
    iters: int = 20,
    ops: Sequence[str] = OPS,
    output: Optional[str] = None,
    dcn: Optional[int] = None,
    hier_mesh: Optional[Mesh] = None,
) -> List[Dict]:
    """One-call benchmark entry (the ``init_processes`` analogue,
    torch_comm_bench.py:144-251).

    Flat and overlap ops run over ``axis`` of ``mesh`` (built over all
    devices when None); hierarchical ops run over ``hier_mesh`` (a
    ``{dcn: dcn, ici: rest}`` mesh built on demand -- the 8-device sim
    gives the 2x4 dcn x ici shape the parity tests pin). ``dcn=None``
    resolves to the physical slice count on multi-slice hardware (the
    only extent the fabric supports) and an emulated 2 elsewhere; on
    real slices the mesh routes through ``MeshSpec.dcn_axes`` ->
    ``build_hybrid_mesh`` so the "dcn" axis is partitioned by physical
    ``slice_index`` -- the dcn-bytes columns must label actual DCN
    traffic, and a plain two-axis ``jax.make_mesh`` over a multi-slice
    device set crashes outright. With ``output=...`` the records land
    as CSV there plus JSONL at the same stem; without, the CSV text
    prints to stdout.
    """
    unknown = [op for op in ops if op not in ALL_OPS]
    if unknown:
        raise ValueError(f"unknown ops {unknown}; choose from {ALL_OPS}")
    flat_ops = [
        op for op in ops if op not in HIER_OPS and op not in RESHARD_OPS
    ]
    hier_ops = [op for op in ops if op in HIER_OPS]
    reshard_ops = [op for op in ops if op in RESHARD_OPS]
    records: List[Dict] = []
    from tpu_hpc.runtime import MeshSpec, build_mesh

    if flat_ops or reshard_ops:
        if mesh is None:
            mesh = build_mesh(MeshSpec(axes={axis: -1}))
    if flat_ops:
        records += CommBenchmark(
            mesh=mesh, axis=axis, sizes=sizes, warmup=warmup,
            iters=iters, ops=flat_ops,
        ).run()
    if reshard_ops:
        records += run_reshard_bench(
            mesh, axis=axis, sizes=sizes, warmup=warmup, iters=iters,
            ops=reshard_ops,
        )
    if hier_ops:
        if hier_mesh is None:
            from tpu_hpc.runtime.mesh import slice_groups, two_tier_spec

            # Follow the flat mesh's extent when one was given: rows
            # from two different world sizes in one artifact would
            # make every cross-op busbw comparison apples-to-oranges.
            # The construction policy itself (dcn resolution,
            # validity, slice-aligned dcn_axes routing on real
            # multi-slice hardware) is runtime.mesh.two_tier_spec --
            # single-sourced with bench.py's --comm-mode path.
            n_dev = jax.device_count() if mesh is None else mesh.size
            n_slices = len(slice_groups(jax.devices()))
            if n_slices > 1 and n_dev != jax.device_count():
                print(
                    f"comm.bench: skipping {hier_ops} -- the "
                    "hierarchical mesh needs the whole multi-slice "
                    f"device set (slice-aligned dcn axis), but the "
                    f"flat mesh spans only {n_dev} of "
                    f"{jax.device_count()} devices",
                    file=sys.stderr,
                )
                hier_ops = []
            else:
                try:
                    # build_mesh is inside the skip handler too: an
                    # explicit --dcn that disagrees with the physical
                    # slice count raises in build_hybrid_mesh, and the
                    # already-measured flat rows must still be written.
                    hier_mesh = build_mesh(
                        two_tier_spec(n_dev, n_slices, dcn=dcn),
                        devices=None if n_dev == jax.device_count()
                        else jax.devices()[:n_dev],
                    )
                except ValueError as e:
                    print(
                        f"comm.bench: skipping {hier_ops} -- {e}",
                        file=sys.stderr,
                    )
                    hier_ops = []
        if hier_ops:
            records += CommBenchmark(
                mesh=hier_mesh, axis="ici", dcn_axis="dcn",
                sizes=sizes, warmup=warmup, iters=iters, ops=hier_ops,
            ).run()
    meta_mesh = mesh if mesh is not None else hier_mesh
    if meta_mesh is None:
        # Every requested op was skipped (hier-only request with no
        # buildable two-tier mesh): nothing measured, nothing to
        # write -- the skip notice above already said why.
        return records
    if output:
        # --output x.jsonl must not have the JSONL overwrite the CSV
        # just written to the same path: the two artifacts always land
        # at <stem>.csv and <stem>.jsonl.
        stem, ext = os.path.splitext(output)
        csv_path = stem + ".csv" if ext == ".jsonl" else output
        jsonl_path = stem + ".jsonl"
        write_csv(records, meta_mesh, csv_path)
        write_jsonl(records, jsonl_path)
        if jax.process_index() == 0:
            print(f"comm bench: wrote {csv_path} and {jsonl_path}")
    else:
        text = write_csv(records, meta_mesh, None)
        if jax.process_index() == 0:
            print(text)
    return records


def main(argv: Optional[Sequence[str]] = None) -> None:
    import argparse

    p = argparse.ArgumentParser(
        description="XLA collective benchmark over ICI/DCN"
    )
    p.add_argument("--sizes", type=int, nargs="+", default=list(DEFAULT_SIZES))
    p.add_argument("--warmup", type=int, default=5)
    p.add_argument("--iters", type=int, default=20)
    p.add_argument("--ops", nargs="+", default=list(ALL_OPS), choices=ALL_OPS)
    p.add_argument(
        "--op", action="append", default=None, choices=ALL_OPS,
        metavar="OP",
        help="bench only this op (repeatable); overrides --ops",
    )
    p.add_argument(
        "--output", type=str, default="comm_bench.csv",
        help="CSV path; a JSONL lands at the same stem ('-' = print "
        "CSV to stdout only)",
    )
    p.add_argument("--axis-size", type=int, default=-1)
    p.add_argument(
        "--emit-table", type=str, default=None, metavar="PATH",
        help="also write a planner-consumable cost table built from "
        "this run's rows (tpu_hpc.comm.planner CostTable JSON). A "
        "directory path writes <fingerprint>.json inside it -- point "
        "it at the planner's cache dir ($TPU_HPC_COMM_TABLES) and "
        "comm_mode='auto' picks the measurements up directly",
    )
    p.add_argument(
        "--dcn", type=int, default=None,
        help="DCN (outer-tier) extent for the hierarchical ops' "
        "(dcn x ici) mesh; default: the physical slice count on "
        "multi-slice hardware, else an emulated 2 (CPU sim / single "
        "slice)",
    )
    args = p.parse_args(argv)

    from tpu_hpc.runtime import MeshSpec, build_mesh, init_distributed

    init_distributed()
    ops = tuple(args.op) if args.op else tuple(args.ops)
    mesh = None
    if any(op not in HIER_OPS for op in ops):
        mesh = build_mesh(MeshSpec(axes={"data": args.axis_size}))
    output = None if args.output == "-" else args.output
    records = run_comm_bench(
        mesh,
        sizes=args.sizes,
        warmup=args.warmup,
        iters=args.iters,
        ops=ops,
        output=output,
        dcn=args.dcn,
    )
    if args.emit_table and jax.process_index() == 0:
        from tpu_hpc.comm import planner

        try:
            # The whole-device-set fingerprint: rows measured on a
            # sub-mesh (--axis-size) key a different topology and are
            # filtered out rather than poisoning the live table.
            table = planner.CostTable.from_rows(
                records, fingerprint=planner.fingerprint_devices()
            )
        except planner.CostTableError as e:
            print(
                f"comm bench: --emit-table skipped -- {e}",
                file=sys.stderr,
            )
        else:
            path = table.save(args.emit_table)
            print(
                f"comm bench: wrote cost table {path} "
                f"({len(table)} entries, fingerprint {table.digest})"
            )


if __name__ == "__main__":
    main()
