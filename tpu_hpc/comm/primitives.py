"""Named collective primitives over a mesh axis.

The TPU-native catalogue matching the reference's NCCL primitive set --
all-reduce, all-gather, reduce-scatter, broadcast, send/recv, all-to-all
(docs/guide/03_communication_primitives.md:161-270). Each helper jits a
``shard_map`` program over one mesh axis, so the same function works on
a real ICI slice or a CPU-simulated mesh.

These exist for three reasons: (1) the comm benchmark suite
(``tpu_hpc.comm.bench``) times exactly these programs; (2) explicit
recipes (ring attention, pipeline, halo) build on the in-shard_map
``jax.lax`` forms; (3) parity so a reference user finds every primitive
by name. Inside ordinary ``jit`` + sharding code you rarely call these
-- XLA inserts collectives for you (SURVEY.md 5.8).
"""
from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P


def _one_axis_program(
    mesh: Mesh, axis: str, body: Callable, in_spec, out_spec
):
    """jit a shard_map program over a single mesh axis."""
    # check_vma=False: collectives like all_gather leave their output
    # marked device-varying even though it is value-replicated; these are
    # single-op programs where the out_spec is the ground truth.
    f = jax.shard_map(
        body, mesh=mesh, in_specs=in_spec, out_specs=out_spec, check_vma=False
    )
    return jax.jit(f)


def all_reduce(mesh: Mesh, axis: str):
    """Sum across ``axis``; every shard gets the total (NCCL allreduce).

    Input: per-device array of shape [n, ...] stacked on ``axis``
    (global shape [n*size, ...]); output: same global shape, every
    shard holding the reduced values (replicated along ``axis``).
    """
    def body(x):
        return jax.lax.psum(x, axis)

    return _one_axis_program(mesh, axis, body, P(axis), P())


def all_gather(mesh: Mesh, axis: str):
    """Concatenate shards along dim 0 on every device (NCCL allgather)."""
    def body(x):
        return jax.lax.all_gather(x, axis, tiled=True)

    return _one_axis_program(mesh, axis, body, P(axis), P())


def reduce_scatter(mesh: Mesh, axis: str):
    """Sum across ``axis`` then scatter dim-0 shards (NCCL reducescatter).

    Input: global [m, ...] replicated along ``axis``; output: [m, ...]
    sharded along ``axis`` with each shard holding its summed slice.
    """
    def body(x):
        return jax.lax.psum_scatter(x, axis, tiled=True)

    return _one_axis_program(mesh, axis, body, P(), P(axis))


def broadcast(mesh: Mesh, axis: str, root: int = 0):
    """Every shard receives root's shard (NCCL broadcast).

    Implemented as a masked psum: zero all non-root shards, sum. On a
    ring this lowers to the same bandwidth class as NCCL's tree/ring
    broadcast and stays a single fused XLA collective.

    HLO cost (pinned by tests/test_hlo_checks.py via
    checks.hlo.collective_counts): the ``jnp.where`` mask is one
    elementwise select over the local payload and the program carries
    exactly ONE all-reduce -- the masking is per-shard predication on
    ``axis_index``, NOT a psum per root candidate, so cost does not
    scale with the axis size.
    """
    def body(x):
        idx = jax.lax.axis_index(axis)
        contrib = jnp.where(idx == root, x, jnp.zeros_like(x))
        return jax.lax.psum(contrib, axis)

    return _one_axis_program(mesh, axis, body, P(axis), P())


def ring_shift(mesh: Mesh, axis: str, shift: int = 1):
    """Neighbor exchange around the ``axis`` ring (the send/recv analogue;
    reference P2P test: tests/send_recv_test.py). Shard i's data moves to
    shard (i+shift) mod n via a single ``ppermute`` riding ICI neighbor
    links."""
    n = mesh.shape[axis]
    perm = [(i, (i + shift) % n) for i in range(n)]

    def body(x):
        return jax.lax.ppermute(x, axis, perm)

    return _one_axis_program(mesh, axis, body, P(axis), P(axis))


def all_to_all(mesh: Mesh, axis: str):
    """Transpose shard dim 0 <-> dim 1 blocks across ``axis`` (NCCL
    alltoall; the Ulysses building block, SURVEY.md 5.7).

    Input globally sharded [n*a, n*b] on dim 0; output sharded on dim 1.
    """
    def body(x):  # local [a, n*b]
        n = jax.lax.axis_size(axis)
        a = x.shape[0]
        xs = x.reshape(a, n, x.shape[1] // n)
        ys = jax.lax.all_to_all(xs, axis, split_axis=1, concat_axis=0)
        return ys.reshape(n * a, x.shape[1] // n)

    return _one_axis_program(mesh, axis, body, P(axis), P(None, axis))
