"""Topology-aware collective planner: measured cost tables + an
alpha-beta fallback.

PR 3 gave the comm layer three gradient-sync strategies (flat /
bucketed_overlap / hierarchical) and a bucket-size knob -- and left the
choice to a static config value the operator hand-tunes per mesh. That
choice IS the latency/bandwidth crossover NCCL's tuner encodes per
(payload, algorithm, fabric) ("Demystifying NCCL", arXiv 2507.04786),
and at fleet scale it must come from *measured* topology cost tables,
not vendor defaults ("Collective Communication for 100k+ GPUs",
arXiv 2510.20171). This module is that tuner for the repo's three
collective consumers:

* the Trainer's gradient sync (``TrainingConfig.comm_mode="auto"``),
* the reshard engine's chunk sizing (``max_inflight_bytes="auto"``),
* the disaggregated-serving KV hop (``--disagg-max-inflight-mb auto``).

Mechanics:

* **Topology fingerprint** -- the cache key: device kind, process
  count, slice count, and the canonical two-tier (dcn x ici) axis
  sizes from :func:`runtime.mesh.two_tier_spec`. Deliberately a
  function of the *device set*, not of any one mesh built over it, so
  one table serves the flat all-reduce AND the hierarchical
  decomposition benched over the same chips. Stable across process
  restarts (pinned in tests/test_planner.py).
* **Cost tables** -- measured (op, dtype) -> [(bytes, seconds)] curves
  from :mod:`tpu_hpc.comm.bench` rows (every row carries the
  fingerprint and dtype; ``--emit-table`` writes a table directly),
  cached on disk at ``<table_dir>/<digest>.json``
  (``$TPU_HPC_COMM_TABLES``, default ``~/.cache/tpu_hpc/comm_tables``).
  Lookups interpolate log-log between measured sizes. A corrupt or
  partial table file degrades to the analytic fallback with a warning
  -- a bad cache must never take down a training run.
* **Alpha-beta fallback** -- per-tier latency + bytes/bandwidth
  (DCN >> ICI in both terms), so :func:`Planner.plan` always answers
  even with zero measurements, and the answer is labeled
  ``source="model"`` so nobody mistakes it for a measurement.

Every decision is a typed :class:`CommDecision` carrying the chosen
mode, bucket bytes, predicted cost, the candidate table, and whether
each number came from measurement or model -- the Trainer logs it as a
schema-stamped ``comm_plan`` obs event, and
``python -m tpu_hpc.comm.planner --explain OP BYTES`` prints the same
reasoning for a human.
"""
from __future__ import annotations

import dataclasses
import hashlib
import json
import math
import os
from typing import Any, Dict, List, Optional, Sequence, Tuple

from tpu_hpc.logging_ import get_logger

ENV_TABLE_DIR = "TPU_HPC_COMM_TABLES"
TABLE_VERSION = 1

# -- the alpha-beta fabric model ---------------------------------------
# Per-tier (launch latency s, bandwidth B/s). The absolute values are
# order-of-magnitude TPU figures (ICI ~100 GB/s links vs DCN ~12.5 GB/s
# per host, collective launch ~us vs cross-slice ~50us); what the
# planner's *decisions* depend on is the documented asymmetry
# (alpha_dcn >> alpha_ici, bw_dcn << bw_ici), which produces exactly
# NCCL's crossover shape: flat wins small payloads (one launch),
# hierarchical wins large ones (1/n_ici of the bytes cross DCN).
# Measured tables override all of this per topology.
TIER_MODEL: Dict[str, Tuple[float, float]] = {
    "ici": (5e-6, 1.0e11),
    "dcn": (5e-5, 1.25e10),
}

# Fraction of a bucket pipeline's collective time the latency-hiding
# scheduler is modeled to hide behind backward compute (buckets after
# the first overlap with the remaining differentiation). One bucket =
# nothing to pipeline = no benefit, so tiny payloads tie with flat and
# the deterministic tie-break below keeps them flat.
OVERLAP_HIDE = 0.5

# Bucket candidates the grad-sync planner chooses among (bytes). The
# config cap (comm_bucket_mb) bounds the ladder from above.
BUCKET_LADDER = tuple(
    int(b * 2 ** 20)
    for b in (0.0625, 0.25, 1, 4, 8, 16, 25, 32, 64)
)

# A chunked move's per-chunk bytes should dwarf the launch latency:
# chunk >= AMORTIZE * alpha * bw makes the alpha overhead <= 1/AMORTIZE
# of each chunk's wire time.
CHUNK_AMORTIZE = 8.0

# Flat ops the analytic model prices over the whole device set. Their
# per-device wire factors are single-sourced from
# comm.bench.wire_factor (the NCCL-tests busbw table); "transfer" is
# one full-payload hop (cross-mesh device_put) and "exchange" rides
# the all_to_all factor.
_FLAT_OPS = (
    "broadcast", "all_reduce", "all_gather", "reduce_scatter",
    "all_to_all", "ring_shift", "transfer", "exchange",
)

# Hierarchical variant of each flat collective (the candidate pairing
# plan() evaluates), and the per-phase launch counts of each
# decomposition (comm.hierarchical: all-reduce = ICI RS + DCN AR + ICI
# AG; the gather/scatter variants run one phase per tier).
_HIER_OF = {
    "all_reduce": "hier_all_reduce",
    "all_gather": "hier_all_gather",
    "reduce_scatter": "hier_reduce_scatter",
}
_HIER_LAUNCHES = {
    "hier_all_reduce": (2, 1),
    "hier_all_gather": (1, 1),
    "hier_reduce_scatter": (1, 1),
}


class CostTableError(ValueError):
    """A cost-table file is corrupt, partial, or mis-keyed."""


# -- topology fingerprint ----------------------------------------------
@dataclasses.dataclass(frozen=True)
class TopologyFingerprint:
    """The cost-table cache key: what the fabric looks like, canonical
    across process restarts and across meshes built over the same
    device set."""

    device_kind: str
    platform: str
    n_devices: int
    n_processes: int
    n_slices: int
    axes: Tuple[Tuple[str, int], ...]
    tiers: Tuple[Tuple[str, str], ...]

    def canonical(self) -> dict:
        return {
            "device_kind": self.device_kind,
            "platform": self.platform,
            "n_devices": self.n_devices,
            "n_processes": self.n_processes,
            "n_slices": self.n_slices,
            "axes": dict(self.axes),
            "tiers": dict(self.tiers),
        }

    @property
    def digest(self) -> str:
        blob = json.dumps(self.canonical(), sort_keys=True)
        return hashlib.sha256(blob.encode()).hexdigest()[:12]

    @property
    def two_tier(self) -> bool:
        """Does the canonical layout expose both fabric tiers?"""
        return any(t == "dcn" for _, t in self.tiers)

    def tier_sizes(self) -> Tuple[int, int]:
        """(n_dcn, n_ici) of the canonical layout; (1, n) when flat."""
        axes = dict(self.axes)
        tiers = dict(self.tiers)
        n_dcn = math.prod(
            v for k, v in axes.items() if tiers.get(k) == "dcn"
        ) if self.two_tier else 1
        n_ici = max(1, self.n_devices // max(n_dcn, 1))
        return n_dcn, n_ici

    def describe(self) -> str:
        axes = ",".join(f"{k}={v}" for k, v in self.axes)
        return (
            f"{self.digest} ({self.device_kind} x{self.n_devices}, "
            f"{self.n_slices} slice(s), axes {axes})"
        )


def fingerprint_devices(
    devices: Optional[Sequence[Any]] = None,
    slices: Optional[int] = None,
) -> TopologyFingerprint:
    """Fingerprint a device set via the canonical two-tier layout.

    The (dcn x ici) axis sizes come from
    :func:`runtime.mesh.two_tier_spec` -- the ONE construction policy
    everything hierarchical already routes through -- so the
    fingerprint cannot drift from what a hierarchical run would
    actually build. Topologies two_tier_spec rejects (n<4, odd counts)
    fingerprint as a flat ``{data: n}`` axis. ``slices`` overrides the
    physical slice count to plan for a *modeled* multi-slice topology
    (the doctor's ``--slices`` idiom); the dcn axis only earns the
    "dcn" tier when the (possibly modeled) slice count exceeds 1 --
    an emulated dcn axis on one physical slice is ICI and is costed
    as such.
    """
    import jax

    from tpu_hpc.runtime.mesh import slice_groups, two_tier_spec

    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    n_dev = len(devices)
    n_slices = (
        int(slices) if slices is not None
        else len(slice_groups(devices))
    )
    d0 = devices[0]
    try:
        spec = two_tier_spec(n_dev, n_slices)
        axes = tuple(spec.resolved_sizes(n_dev).items())
    except ValueError:
        axes = (("data", n_dev),)
    tiers = tuple(
        (name, "dcn" if name == "dcn" and n_slices > 1 else "ici")
        for name, _ in axes
    )
    return TopologyFingerprint(
        device_kind=getattr(d0, "device_kind", "unknown"),
        platform=getattr(d0, "platform", "unknown"),
        n_devices=n_dev,
        n_processes=jax.process_count(),
        n_slices=n_slices,
        axes=axes,
        tiers=tiers,
    )


def fingerprint_mesh(mesh) -> TopologyFingerprint:
    """Fingerprint of the device set under a mesh (NOT the mesh's own
    axis layout: the flat and hierarchical benchmarks over one pod
    must share a table)."""
    return fingerprint_devices(list(mesh.devices.flat))


# -- measured cost tables ----------------------------------------------
@dataclasses.dataclass
class CostTable:
    """Measured (op, dtype) -> [(bytes, seconds)] curves for one
    topology fingerprint."""

    fingerprint: dict
    digest: str
    entries: Dict[Tuple[str, str], List[Tuple[int, float]]] = (
        dataclasses.field(default_factory=dict)
    )
    path: Optional[str] = None

    def add(self, op: str, dtype: str, nbytes: int, mean_s: float) -> None:
        if nbytes <= 0 or mean_s <= 0:
            return
        curve = self.entries.setdefault((op, str(dtype)), [])
        curve.append((int(nbytes), float(mean_s)))
        curve.sort()

    def __len__(self) -> int:
        return sum(len(v) for v in self.entries.values())

    @property
    def ops(self) -> Tuple[str, ...]:
        return tuple(sorted({op for op, _ in self.entries}))

    def lookup(
        self, op: str, dtype: str, nbytes: int
    ) -> Optional[float]:
        """Interpolated measured cost, or None when the table has no
        curve for (op, dtype). Interpolation is log-log between the
        bracketing measured sizes (collective time over payload decades
        is near-linear in that space); beyond the measured range the
        end segment's slope extrapolates -- labeled measured because
        the slope is."""
        curve = self.entries.get((op, str(dtype)))
        if not curve:
            return None
        if len(curve) == 1:
            # One point: scale by the bandwidth term it implies.
            b0, t0 = curve[0]
            return t0 * max(nbytes, 1) / b0
        pts = [(math.log(b), math.log(t)) for b, t in curve]
        x = math.log(max(nbytes, 1))
        if x <= pts[0][0]:
            (x0, y0), (x1, y1) = pts[0], pts[1]
        elif x >= pts[-1][0]:
            (x0, y0), (x1, y1) = pts[-2], pts[-1]
        else:
            for (x0, y0), (x1, y1) in zip(pts, pts[1:]):
                if x0 <= x <= x1:
                    break
        if x1 == x0:
            return math.exp(y0)
        y = y0 + (y1 - y0) * (x - x0) / (x1 - x0)
        return math.exp(y)

    # -- (de)serialization --------------------------------------------
    def to_json(self) -> dict:
        return {
            "table_version": TABLE_VERSION,
            "fingerprint": self.fingerprint,
            "digest": self.digest,
            "entries": [
                {"op": op, "dtype": dt, "bytes": b, "mean_s": t}
                for (op, dt), curve in sorted(self.entries.items())
                for b, t in curve
            ],
        }

    @classmethod
    def from_json(cls, data: Any, path: Optional[str] = None) -> "CostTable":
        if not isinstance(data, dict):
            raise CostTableError(
                f"cost table must be a JSON object, got "
                f"{type(data).__name__}"
            )
        if data.get("table_version") != TABLE_VERSION:
            raise CostTableError(
                f"table_version {data.get('table_version')!r} != "
                f"{TABLE_VERSION}"
            )
        for field in ("fingerprint", "digest", "entries"):
            if field not in data:
                raise CostTableError(f"cost table missing {field!r}")
        table = cls(
            fingerprint=data["fingerprint"], digest=data["digest"],
            path=path,
        )
        for i, e in enumerate(data["entries"]):
            try:
                table.add(e["op"], e["dtype"], e["bytes"], e["mean_s"])
            except (TypeError, KeyError) as err:
                raise CostTableError(
                    f"entry {i} malformed: {err!r}"
                ) from None
        return table

    def save(self, path: str) -> str:
        """Write the table (atomic; a crash mid-write must not leave a
        torn table the loader would then warn about forever). Any path
        not ending in ``.json`` is treated as a directory (created if
        needed) and gets ``<digest>.json`` inside it -- the cache
        layout :func:`load_cached` reads."""
        if not path.endswith(".json"):
            path = os.path.join(path, f"{self.digest}.json")
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = f"{path}.tmp"
        with open(tmp, "w") as f:
            json.dump(self.to_json(), f, indent=1)
        os.replace(tmp, path)
        self.path = path
        return path

    @classmethod
    def from_rows(
        cls,
        rows: Sequence[dict],
        fingerprint: Optional[TopologyFingerprint] = None,
    ) -> "CostTable":
        """Build a table from comm-bench records (each row carries
        ``op``/``dtype``/``bytes_per_shard``/``mean_s`` and its
        ``fingerprint`` digest). Rows whose digest disagrees with the
        majority (or with ``fingerprint`` when given) are rejected --
        a table silently mixing topologies would be worse than none.
        """
        usable = [
            r for r in rows
            if r.get("op") and r.get("bytes_per_shard")
            and r.get("mean_s") and r.get("fingerprint")
        ]
        if not usable:
            raise CostTableError(
                "no bench rows carry (op, bytes_per_shard, mean_s, "
                "fingerprint) -- re-run tpu_hpc.comm.bench to emit "
                "planner-keyed rows"
            )
        digests = {r["fingerprint"] for r in usable}
        if fingerprint is not None:
            digest, canon = fingerprint.digest, fingerprint.canonical()
        elif len(digests) == 1:
            digest = digests.pop()
            canon = usable[0].get("fingerprint_topology") or {}
        else:
            raise CostTableError(
                f"rows span {len(digests)} fingerprints "
                f"({sorted(digests)}); pass the one to keep"
            )
        table = cls(fingerprint=canon, digest=digest)
        for r in usable:
            if r["fingerprint"] != digest:
                continue
            table.add(
                r["op"], r.get("dtype", "float32"),
                r["bytes_per_shard"], r["mean_s"],
            )
        if not len(table):
            raise CostTableError(
                f"no rows matched fingerprint {digest}"
            )
        return table


def table_dir(override: Optional[str] = None) -> str:
    return (
        override
        or os.environ.get(ENV_TABLE_DIR)
        or os.path.join(
            os.path.expanduser("~"), ".cache", "tpu_hpc", "comm_tables"
        )
    )


def load_table(path: str) -> CostTable:
    """Load one table file; raises :class:`CostTableError` on corrupt
    or partial content."""
    try:
        with open(path) as f:
            data = json.load(f)
    except OSError as e:
        raise CostTableError(f"{path}: {e}") from None
    except ValueError as e:
        raise CostTableError(f"{path}: not JSON ({e})") from None
    return CostTable.from_json(data, path=path)


def load_cached(
    fp: TopologyFingerprint, table_dir_: Optional[str] = None
) -> Optional[CostTable]:
    """The cached table for this topology, or None (absent, or corrupt
    -- the latter with a warning: the planner must degrade to the
    analytic fallback, never crash its consumer)."""
    path = os.path.join(table_dir(table_dir_), f"{fp.digest}.json")
    if not os.path.exists(path):
        return None
    try:
        return load_table(path)
    except CostTableError as e:
        get_logger("tpu_hpc.comm.planner").warning(
            "ignoring corrupt cost table %s (%s); planner falls back "
            "to the alpha-beta model -- delete or re-emit the table",
            path, e,
        )
        return None


# -- analytic fallback -------------------------------------------------
def tier_cost(tier: str, nbytes: float) -> float:
    """alpha + bytes/bw for one launch over one tier. Strictly
    increasing in bytes; at equal bytes the DCN tier is strictly
    costlier than ICI (both pinned in tests)."""
    alpha, bw = TIER_MODEL[tier]
    return alpha + nbytes / bw


def model_cost(op: str, nbytes: int, fp: TopologyFingerprint) -> float:
    """Analytic cost of one ``op`` at per-shard payload ``nbytes`` on
    the fingerprinted topology. The bottleneck tier of a flat op is
    DCN whenever the device set spans slices (a flat collective ships
    its full wire share cross-slice); hierarchical ops split their
    bytes per phase exactly like :func:`comm.bench.two_phase_bytes`.
    """
    n = fp.n_devices
    if op in _FLAT_OPS:
        if n <= 1 and op not in ("transfer",):
            return 0.0
        tier = "dcn" if fp.n_slices > 1 else "ici"
        if op == "transfer":
            # Cross-mesh device_put: one hop of the full payload over
            # the slower fabric (disjoint tiers talk over DCN on real
            # pods; ICI when everything is one slice).
            return tier_cost(tier, nbytes)
        from tpu_hpc.comm.bench import wire_factor

        key = "all_to_all" if op == "exchange" else op
        return tier_cost(tier, wire_factor(key, n) * nbytes)
    if op in _HIER_LAUNCHES:
        if not fp.two_tier:
            raise ValueError(
                f"{op} needs a two-tier topology; fingerprint "
                f"{fp.digest} is flat"
            )
        from tpu_hpc.comm.bench import two_phase_bytes

        n_dcn, n_ici = fp.tier_sizes()
        ici_b, dcn_b = two_phase_bytes(op, nbytes, n_dcn, n_ici)
        l_ici, l_dcn = _HIER_LAUNCHES[op]
        a_ici, bw_ici = TIER_MODEL["ici"]
        a_dcn, bw_dcn = TIER_MODEL[
            "dcn" if fp.n_slices > 1 else "ici"
        ]
        return (
            l_ici * a_ici + ici_b / bw_ici
            + l_dcn * a_dcn + dcn_b / bw_dcn
        )
    raise ValueError(f"unknown op {op!r} for the analytic model")


# -- decisions ---------------------------------------------------------
@dataclasses.dataclass(frozen=True)
class CommDecision:
    """A planner verdict: what to run and why. ``source`` says where
    the winning number came from -- "measured" (cost table),
    "model" (alpha-beta fallback) or "constraint" (only one legal
    choice, no cost comparison ran)."""

    op: str
    payload_bytes: int
    dtype: str
    mode: str
    bucket_bytes: Optional[int]
    predicted_cost_s: float
    source: str
    fingerprint: str
    table: Optional[str]
    candidates: Tuple[Dict[str, Any], ...]
    reason: str = ""

    def summary(self) -> dict:
        """JSON-safe decision record -- the ``comm_plan`` obs event
        payload."""
        rec = {
            "op": self.op,
            "mode": self.mode,
            "source": self.source,
            "payload_bytes": int(self.payload_bytes),
            "dtype": self.dtype,
            "predicted_cost_ms": round(self.predicted_cost_s * 1e3, 6),
            "fingerprint": self.fingerprint,
            "candidates": [dict(c) for c in self.candidates],
        }
        if self.bucket_bytes is not None:
            rec["bucket_bytes"] = int(self.bucket_bytes)
        if self.table:
            rec["table"] = self.table
        if self.reason:
            rec["reason"] = self.reason
        return rec

    def explain(self) -> str:
        lines = [
            f"decision: op={self.op} payload={self.payload_bytes} B "
            f"dtype={self.dtype} -> mode={self.mode}"
            + (
                f" bucket={self.bucket_bytes // 2 ** 10} KiB"
                if self.bucket_bytes else ""
            )
            + f" pred={self.predicted_cost_s * 1e3:.4f} ms "
            f"[{self.source}]",
        ]
        if self.reason:
            lines.append(f"  reason: {self.reason}")
        if self.candidates:
            lines.append("candidates:")
            for c in self.candidates:
                lines.append(
                    f"  {c['mode']:<18} "
                    f"{c['cost_ms']:>12.4f} ms  [{c['source']}]"
                )
        return "\n".join(lines)


@dataclasses.dataclass
class Planner:
    """Cost-table-driven collective planner for one topology."""

    fingerprint: TopologyFingerprint
    table: Optional[CostTable] = None

    @classmethod
    def for_devices(
        cls,
        devices: Optional[Sequence[Any]] = None,
        slices: Optional[int] = None,
        table_dir: Optional[str] = None,
        table: Optional[CostTable] = None,
    ) -> "Planner":
        fp = fingerprint_devices(devices, slices=slices)
        if table is None:
            table = load_cached(fp, table_dir)
        return cls(fingerprint=fp, table=table)

    @classmethod
    def for_mesh(
        cls,
        mesh,
        table_dir: Optional[str] = None,
        table: Optional[CostTable] = None,
    ) -> "Planner":
        fp = fingerprint_mesh(mesh)
        if table is None:
            table = load_cached(fp, table_dir)
        return cls(fingerprint=fp, table=table)

    # -- cost resolution ----------------------------------------------
    def cost(
        self, op: str, nbytes: int, dtype: str = "float32"
    ) -> Tuple[float, str]:
        """(seconds, source): the measured curve when the table has
        one for (op, dtype), else the alpha-beta model."""
        if self.table is not None:
            t = self.table.lookup(op, dtype, nbytes)
            if t is not None:
                return t, "measured"
        return model_cost(op, nbytes, self.fingerprint), "model"

    # -- generic collective choice ------------------------------------
    def plan(
        self, op: str, nbytes: int, dtype: str = "float32"
    ) -> CommDecision:
        """Flat vs hierarchical for one collective at one payload.

        ``op`` is the flat collective name (comm.bench vocabulary);
        the hierarchical variant is a candidate whenever the topology
        exposes both tiers OR the table measured it (a sim-mesh table
        carries hier rows even though its fingerprint is one slice).
        """
        cands: List[Dict[str, Any]] = []
        c, src = self.cost(op, nbytes, dtype)
        cands.append({
            "mode": "flat", "cost_ms": round(c * 1e3, 6),
            "cost_s": c, "source": src,
        })
        hier = _HIER_OF.get(op)
        if hier is not None:
            measured = (
                self.table is not None
                and self.table.lookup(hier, dtype, nbytes) is not None
            )
            if measured or self.fingerprint.two_tier:
                hc, hsrc = self.cost(hier, nbytes, dtype)
                cands.append({
                    "mode": "hierarchical",
                    "cost_ms": round(hc * 1e3, 6),
                    "cost_s": hc, "source": hsrc,
                })
        best = min(cands, key=lambda c: c["cost_s"])  # ties: flat first
        return CommDecision(
            op=op, payload_bytes=nbytes, dtype=dtype,
            mode=best["mode"], bucket_bytes=None,
            predicted_cost_s=best["cost_s"], source=best["source"],
            fingerprint=self.fingerprint.digest,
            table=getattr(self.table, "path", None),
            candidates=tuple(
                {k: v for k, v in c.items() if k != "cost_s"}
                for c in cands
            ),
        )

    # -- gradient sync (the Trainer consumer) -------------------------
    def _bucketed_cost(
        self,
        op: str,
        payload: int,
        bucket: int,
        dtype: str,
    ) -> Tuple[float, str]:
        """Modeled pipeline cost of syncing ``payload`` bytes in
        ``bucket``-sized pieces: every bucket pays its own collective,
        but buckets after the first overlap with backward compute
        (OVERLAP_HIDE of their time hides)."""
        n_b = max(1, -(-payload // bucket))
        per, src = self.cost(op, min(bucket, payload), dtype)
        total = n_b * per
        hidden = OVERLAP_HIDE * (1.0 - 1.0 / n_b)
        return total * (1.0 - hidden), src

    def bucket_bytes_for(
        self,
        op: str,
        payload: int,
        dtype: str = "float32",
        cap: Optional[int] = None,
    ) -> int:
        """The bucket size minimizing the modeled pipeline cost over
        the ladder (capped by the config knob)."""
        cap = cap or BUCKET_LADDER[-1]
        ladder = sorted(
            {b for b in BUCKET_LADDER if b <= cap} | {cap}
        )
        best = min(
            ladder,
            key=lambda b: self._bucketed_cost(op, payload, b, dtype)[0],
        )
        return best

    def plan_grad_sync(
        self,
        payload_bytes: int,
        dtype: str = "float32",
        params_sharded: bool = False,
        two_tier: bool = False,
        bucket_cap_bytes: Optional[int] = None,
        constraint_reason: Optional[str] = None,
    ) -> CommDecision:
        """Choose the Trainer's gradient-sync mode + bucket size.

        ``params_sharded`` forces flat (FSDP/TP plans keep GSPMD's
        fused collectives -- fsdp.validate_grad_sync_mode's rule);
        ``constraint_reason`` forces flat for any OTHER structural
        reason, recorded verbatim (the comm_plan event exists so
        sweeps can attribute the planner's reasoning -- a wrong cause
        sends the operator to the wrong knob). ``two_tier`` admits
        the hierarchical candidate (the batch must shard over
        (dcn, ici) axes for it to be runnable at all). Ties break
        toward the earlier candidate -- flat beats a manual mode that
        merely matches it.
        """
        if params_sharded or constraint_reason is not None:
            c, src = self.cost("all_reduce", payload_bytes, dtype)
            return CommDecision(
                op="grad_sync", payload_bytes=payload_bytes,
                dtype=dtype, mode="flat", bucket_bytes=None,
                predicted_cost_s=c, source="constraint",
                fingerprint=self.fingerprint.digest,
                table=getattr(self.table, "path", None),
                candidates=({
                    "mode": "flat", "cost_ms": round(c * 1e3, 6),
                    "source": src,
                },),
                reason=(
                    "params are sharded (FSDP/TP): manual sync modes "
                    "need replicated params, GSPMD owns these "
                    "collectives"
                ) if params_sharded else constraint_reason,
            )
        cands: List[Dict[str, Any]] = []
        c, src = self.cost("all_reduce", payload_bytes, dtype)
        cands.append({
            "mode": "flat", "cost_ms": round(c * 1e3, 6),
            "cost_s": c, "source": src, "bucket_bytes": None,
        })
        bucket = self.bucket_bytes_for(
            "all_reduce", payload_bytes, dtype, cap=bucket_cap_bytes
        )
        bc, bsrc = self._bucketed_cost(
            "all_reduce", payload_bytes, bucket, dtype
        )
        cands.append({
            "mode": "bucketed_overlap", "cost_ms": round(bc * 1e3, 6),
            "cost_s": bc, "source": bsrc, "bucket_bytes": bucket,
        })
        hier_available = two_tier and (
            self.fingerprint.two_tier
            or (
                self.table is not None
                and self.table.lookup(
                    "hier_all_reduce", dtype, payload_bytes
                ) is not None
            )
        )
        if hier_available:
            hbucket = self.bucket_bytes_for(
                "hier_all_reduce", payload_bytes, dtype,
                cap=bucket_cap_bytes,
            )
            hc, hsrc = self._bucketed_cost(
                "hier_all_reduce", payload_bytes, hbucket, dtype
            )
            cands.append({
                "mode": "hierarchical", "cost_ms": round(hc * 1e3, 6),
                "cost_s": hc, "source": hsrc, "bucket_bytes": hbucket,
            })
        best = min(cands, key=lambda c: c["cost_s"])
        return CommDecision(
            op="grad_sync", payload_bytes=payload_bytes, dtype=dtype,
            mode=best["mode"], bucket_bytes=best["bucket_bytes"],
            predicted_cost_s=best["cost_s"], source=best["source"],
            fingerprint=self.fingerprint.digest,
            table=getattr(self.table, "path", None),
            candidates=tuple(
                {k: v for k, v in c.items() if k != "cost_s"}
                for c in cands
            ),
        )

    # -- chunk sizing (reshard + disagg consumers) --------------------
    def chunk_bytes(self, total_bytes: int) -> int:
        """Recommended per-chunk transient for a bounded move of
        ``total_bytes``: big enough that launch latency amortizes
        (chunk wire time >= CHUNK_AMORTIZE x alpha), no bigger than
        the move itself. The fabric tier follows the fingerprint --
        build the planner over exactly the devices the move touches
        (reshard does: the union of source and target meshes), and a
        device set spanning slices amortizes against the DCN alpha."""
        tier = "dcn" if self.fingerprint.n_slices > 1 else "ici"
        alpha, bw = TIER_MODEL[tier]
        floor = int(CHUNK_AMORTIZE * alpha * bw)
        # Round up to the next power of two: chunk counts stay stable
        # under small payload drift (stable chunk specs = stable
        # compiled-program cache keys in the reshard executor).
        chunk = 1 << max(floor - 1, 1).bit_length()
        return max(1, min(chunk, max(int(total_bytes), 1)))


# -- the Trainer hook --------------------------------------------------
def plan_trainer_grad_sync(
    mesh,
    batch_pspec,
    param_pspecs,
    params,
    bucket_cap_bytes: Optional[int] = None,
    table_dir: Optional[str] = None,
) -> CommDecision:
    """Resolve ``comm_mode="auto"`` for a Trainer: inspects the
    sharding plan (sharded params force flat), the batch pspec (two
    sync axes admit hierarchical), and the exact gradient payload, and
    asks the topology's planner."""
    import jax
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from tpu_hpc.comm import overlap

    sharded = any(
        any(entry is not None for entry in spec)
        for spec in jax.tree.leaves(
            param_pspecs, is_leaf=lambda x: isinstance(x, P)
        )
    )
    constraint = None
    try:
        sync_axes = overlap.sync_axes_from_batch_pspec(batch_pspec)
    except ValueError:
        # Nothing to sync over: keep GSPMD's program -- and say THAT,
        # not "params are sharded" (a false cause in the comm_plan
        # event would send the operator to the wrong knob).
        sync_axes = ()
        constraint = (
            "the batch pspec shards the batch over no mesh axis: "
            "there is no data-parallel gradient sync to plan"
        )
    leaves = jax.tree.leaves(params)
    payload = sum(
        int(math.prod(leaf.shape)) * np.dtype(leaf.dtype).itemsize
        for leaf in leaves
    )
    dtype = str(np.dtype(leaves[0].dtype)) if leaves else "float32"
    planner = Planner.for_mesh(mesh, table_dir=table_dir)
    return planner.plan_grad_sync(
        payload, dtype=dtype, params_sharded=sharded,
        two_tier=len(sync_axes) == 2,
        bucket_cap_bytes=bucket_cap_bytes,
        constraint_reason=constraint,
    )


# -- cost-table inventory (the doctor line) ----------------------------
def table_inventory(
    table_dir_: Optional[str] = None,
    devices: Optional[Sequence[Any]] = None,
    slices: Optional[int] = None,
) -> dict:
    """What the planner would find for the live topology:
    ``status`` in {"measured", "stale", "absent"} plus the fingerprint
    and (when measured) entry/op counts -- the ``checks.doctor``
    inventory line."""
    fp = fingerprint_devices(devices, slices=slices)
    d = table_dir(table_dir_)
    others = []
    if os.path.isdir(d):
        others = [f for f in sorted(os.listdir(d))
                  if f.endswith(".json")]
    path = os.path.join(d, f"{fp.digest}.json")
    inv = {
        "fingerprint": fp.digest,
        "topology": fp.describe(),
        "table_dir": d,
        "n_tables": len(others),
    }
    if os.path.exists(path):
        table = load_cached(fp, table_dir_)
        if table is None:
            inv.update(status="stale", detail="corrupt table file")
        else:
            inv.update(
                status="measured", path=path, entries=len(table),
                ops=list(table.ops),
            )
    elif others:
        inv.update(status="stale")
    else:
        inv.update(status="absent")
    return inv


def format_inventory(inv: dict) -> str:
    s = inv["status"]
    head = f"comm cost tables: fingerprint {inv['topology']} -- "
    if s == "measured":
        return head + (
            f"measured table ({inv['entries']} entries: "
            f"{', '.join(inv['ops'])}) at {inv['path']}"
        )
    if s == "stale":
        return head + (
            f"stale ({inv['n_tables']} table(s) in {inv['table_dir']} "
            "for other topologies"
            + (
                " or corrupt" if inv.get("detail") else ""
            )
            + "); planner answers from the alpha-beta model -- "
            "re-measure with `python -m tpu_hpc.comm.bench "
            "--emit-table " + inv["table_dir"] + "`"
        )
    return head + (
        "absent; planner answers from the alpha-beta model -- "
        "measure with `python -m tpu_hpc.comm.bench --emit-table "
        + inv["table_dir"] + "`"
    )


# -- CLI ---------------------------------------------------------------
def _sweep_rows(
    planner: Planner, op: str, sizes: Sequence[int], dtype: str
) -> List[dict]:
    """Schema-stamped bench rows of planner decisions across payload
    sizes -- the banked crossover evidence. The size rides IN the
    metric name (the bank gate reduces per metric; see
    comm/bench.py's reshard rows for the original lesson)."""
    from tpu_hpc.obs.schema import stamp

    rows = []
    for size in sizes:
        d = planner.plan(op, size, dtype)
        flat = next(
            c for c in d.candidates if c["mode"] == "flat"
        )
        row = {
            "event": "bench",
            "metric": f"comm_planner_{op}_n{size}_pred_ms",
            "value": round(d.predicted_cost_s * 1e3, 6),
            "unit": "ms",
            "op": op,
            "payload_bytes": size,
            "dtype": dtype,
            "mode": d.mode,
            "source": d.source,
            "fingerprint": d.fingerprint,
            "flat_pred_ms": flat["cost_ms"],
        }
        hier = [
            c for c in d.candidates if c["mode"] == "hierarchical"
        ]
        if hier:
            row["hier_pred_ms"] = hier[0]["cost_ms"]
        rows.append(stamp(row))
    return rows


def main(argv: Optional[Sequence[str]] = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        description="topology-aware collective planner: explain a "
        "decision or sweep the crossover",
    )
    ap.add_argument(
        "--explain", nargs=2, metavar=("OP", "BYTES"), default=None,
        help="print the decision, candidate costs, and which table "
        "(or fallback) supplied them, for one (op, payload)",
    )
    ap.add_argument(
        "--sweep", type=int, nargs="+", metavar="BYTES", default=None,
        help="emit schema-stamped bench rows of the decision at each "
        "payload size (the banked crossover evidence)",
    )
    ap.add_argument(
        "--op", default="all_reduce",
        help="collective for --sweep (default: all_reduce)",
    )
    ap.add_argument("--dtype", default="float32")
    ap.add_argument(
        "--slices", type=int, default=None,
        help="model this many slices instead of the physical count "
        "(the doctor's --slices idiom: plan for a topology you do "
        "not have attached)",
    )
    ap.add_argument(
        "--table", default=None, metavar="PATH",
        help="explicit cost-table file (default: the cache dir entry "
        "for the live fingerprint)",
    )
    ap.add_argument(
        "--table-dir", default=None, metavar="DIR",
        help=f"cost-table cache dir (default: ${ENV_TABLE_DIR} or "
        "~/.cache/tpu_hpc/comm_tables)",
    )
    ap.add_argument(
        "--output", default=None, metavar="PATH",
        help="write --sweep rows as JSONL here (default: stdout)",
    )
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)

    if (args.explain is None) == (args.sweep is None):
        ap.error("exactly one of --explain / --sweep is required")
    if args.output and args.sweep is None:
        # The misplaced-flag discipline: an output path the selected
        # action never writes must be an error, not a silent no-file.
        ap.error("--output is only consumed by --sweep")
    if args.table and args.table_dir:
        ap.error("--table and --table-dir are mutually exclusive")

    table = None
    if args.table:
        table = load_table(args.table)  # explicit: corrupt IS fatal
    planner = Planner.for_devices(
        slices=args.slices, table_dir=args.table_dir, table=table
    )

    if args.explain is not None:
        op, nbytes = args.explain[0], int(args.explain[1])
        decision = (
            planner.plan_grad_sync(
                nbytes, dtype=args.dtype,
                two_tier=planner.fingerprint.two_tier,
            )
            if op == "grad_sync"
            else planner.plan(op, nbytes, args.dtype)
        )
        if args.json:
            print(json.dumps(decision.summary(), indent=1))
            return 0
        print(f"comm planner @ {planner.fingerprint.describe()}")
        t = planner.table
        print(
            f"table: measured {t.path} ({len(t)} entries)" if t
            else "table: absent -> alpha-beta fallback"
        )
        print(decision.explain())
        return 0

    rows = _sweep_rows(planner, args.op, args.sweep, args.dtype)
    text = "\n".join(json.dumps(r) for r in rows)
    if args.output:
        with open(args.output, "w") as f:
            f.write(text + "\n")
        modes = [r["mode"] for r in rows]
        print(
            f"planner sweep: wrote {len(rows)} rows to "
            f"{args.output} (modes: {' '.join(modes)})"
        )
    else:
        print(text)
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
