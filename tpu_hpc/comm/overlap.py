"""Communication/compute overlap: bucketed gradient sync + pipelined
parameter gathers.

Two latency-hiding idioms from the reference's world, done TPU-natively:

* **Bucketed gradient synchronization** -- the DDP gradient-bucketing
  idiom (the reference's DDP wraps grads into ~25 MB buckets and
  all-reduces each as backward produces it). Under GSPMD the gradient
  reduction is one fused collective XLA schedules where it likes;
  here the step computes per-shard gradients explicitly inside
  ``shard_map`` and reduces them in size-capped buckets -- separate
  collectives the latency-hiding scheduler can overlap with the
  remaining backward compute, and (in hierarchical mode) whose ICI
  and DCN phases pipeline across buckets: bucket k's DCN hop rides
  behind bucket k+1's ICI reduce-scatter.
* **ppermute-pipelined all-gather / gather-matmul** -- the
  collective-matmul decomposition (Wang et al.): an FSDP-style
  parameter gather fused into the consuming matmul as a ring of
  ``ppermute`` hops, each hop overlapped with the partial matmul of
  the shard already in hand. ``y = x @ W`` with ``W`` sharded over the
  data axis never materializes the gathered ``W``.

The bucketed sync is what the Trainer's ``comm_mode`` modes
("bucketed_overlap", "hierarchical") run; the standalone program
wrappers at the bottom are what ``tpu_hpc.comm.bench`` times.
"""
from __future__ import annotations

import math
from typing import Any, Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from tpu_hpc.comm.hierarchical import psum_two_phase

# DDP's default bucket cap; the same size works here (big enough to
# amortize per-collective latency, small enough that several buckets
# pipeline within one backward).
DEFAULT_BUCKET_BYTES = 25 * 2 ** 20


def sync_axes_from_batch_pspec(batch_pspec) -> Tuple[str, ...]:
    """The mesh axes a gradient sync must reduce over: every axis the
    batch's leading dim shards across. ``P('data')`` -> ('data',);
    ``P(('dcn', 'data'))`` -> ('dcn', 'data') -- for hierarchical
    mode the outer name is the DCN tier, matching the mesh layout
    convention (DCN component slowest)."""
    leaves = jax.tree.leaves(
        batch_pspec, is_leaf=lambda x: isinstance(x, P)
    )
    axes: List[str] = []
    for spec in leaves:
        if len(spec) == 0 or spec[0] is None:
            continue
        entry = spec[0]
        for name in (entry if isinstance(entry, tuple) else (entry,)):
            if name not in axes:
                axes.append(name)
    if not axes:
        raise ValueError(
            f"batch pspec {batch_pspec} shards the batch over no mesh "
            "axis; manual gradient sync has nothing to reduce over"
        )
    return tuple(axes)


def assign_buckets(leaves: Sequence[Any], bucket_bytes: int) -> List[List[int]]:
    """Partition leaf indices into size-capped, dtype-homogeneous
    buckets, walking the tree in REVERSE traversal order -- the DDP
    convention: backward produces gradients for the last layers first,
    so reverse-order buckets fill (and their collectives launch) while
    earlier layers are still differentiating.

    Every bucket holds >= 1 leaf (a single leaf larger than the cap
    gets its own bucket); dtype changes always cut a bucket (the
    flattened bucket payload is one concatenated vector).
    """
    if bucket_bytes <= 0:
        raise ValueError(f"bucket_bytes must be > 0, got {bucket_bytes}")
    buckets: List[List[int]] = []
    cur: List[int] = []
    cur_bytes = 0
    cur_dtype = None
    for i in reversed(range(len(leaves))):
        leaf = leaves[i]
        nbytes = int(math.prod(leaf.shape)) * jnp.dtype(leaf.dtype).itemsize
        if cur and (
            jnp.dtype(leaf.dtype) != cur_dtype
            or cur_bytes + nbytes > bucket_bytes
        ):
            buckets.append(cur)
            cur, cur_bytes = [], 0
        cur.append(i)
        cur_bytes += nbytes
        cur_dtype = jnp.dtype(leaf.dtype)
    if cur:
        buckets.append(cur)
    return buckets


def make_bucket_sync(
    template: Any,
    mesh: Mesh,
    sync_axes: Tuple[str, ...],
    mode: str = "bucketed_overlap",
    bucket_bytes: int = DEFAULT_BUCKET_BYTES,
) -> Callable[[Any], Any]:
    """Build the in-``shard_map`` gradient-mean: per-bucket psum over
    ``sync_axes`` divided by the total extent (gradient of the global
    mean = mean of per-shard gradients).

    ``mode="bucketed_overlap"``: one flat psum per bucket over all
    sync axes. ``mode="hierarchical"``: each bucket reduces via the
    two-phase decomposition (``sync_axes`` = (dcn, ici), outer first)
    -- 1/n_ici of every bucket crosses DCN, and distinct buckets'
    phases pipeline. The returned callable must run INSIDE a
    shard_map over ``mesh`` (it calls ``jax.lax`` collectives).
    """
    if mode == "hierarchical" and len(sync_axes) != 2:
        raise ValueError(
            f"hierarchical sync needs exactly two sync axes "
            f"(dcn, ici); the batch shards over {sync_axes}"
        )
    leaves, treedef = jax.tree.flatten(template)
    buckets = assign_buckets(leaves, bucket_bytes)
    n_total = math.prod(mesh.shape[a] for a in sync_axes)
    if mode == "hierarchical":
        n_dcn, n_ici = (mesh.shape[a] for a in sync_axes)

    def sync(grads):
        flat = jax.tree.leaves(grads)
        out: List[Any] = [None] * len(flat)
        for bucket in buckets:
            vec = jnp.concatenate([flat[i].reshape(-1) for i in bucket])
            if mode == "hierarchical":
                vec = psum_two_phase(
                    vec, sync_axes[0], sync_axes[1],
                    n_dcn=n_dcn, n_ici=n_ici,
                )
            else:
                vec = jax.lax.psum(
                    vec,
                    sync_axes if len(sync_axes) > 1 else sync_axes[0],
                )
            vec = vec / n_total
            offset = 0
            for i in bucket:
                size = flat[i].size
                out[i] = vec[offset:offset + size].reshape(flat[i].shape)
                offset += size
        return treedef.unflatten(out)

    return sync


def make_synced_value_and_grad(
    forward: Callable,
    mesh: Mesh,
    batch_pspec,
    params_template: Any,
    mode: str,
    bucket_bytes: int = DEFAULT_BUCKET_BYTES,
) -> Callable:
    """A drop-in for the step's ``value_and_grad`` that owns gradient
    synchronization instead of leaving it to GSPMD.

    Runs forward/backward inside one ``shard_map`` over the mesh:
    params replicated (validated by the caller --
    ``fsdp.validate_grad_sync_mode``), batch sharded per
    ``batch_pspec``, gradients per-shard until the bucketed sync
    reduces them IN the same program -- so XLA sees backward compute
    and bucket collectives together and its latency-hiding scheduler
    can overlap them. Loss and aux/model-state leaves are
    ``pmean``-ed over the sync axes, making the returned values
    global exactly like the GSPMD path's (mean of per-shard means ==
    global-batch mean at equal shard sizes); non-inexact leaves are
    rejected at trace time (no reduction is universally correct for
    them). The replicated step rng gets the shard index folded in, so
    rng-consuming forwards draw decorrelated randomness per shard.

    Signature of the returned fn: ``(params, model_state, batch,
    rng) -> ((loss, (new_model_state, aux)), grads)`` -- the contract
    ``train.trainer.make_step_fn`` consumes for both the plain and
    grad-accumulated branches (psum is linear, so syncing each
    microbatch's gradient and summing equals syncing the sum).
    """
    sync_axes = sync_axes_from_batch_pspec(batch_pspec)
    sync = make_bucket_sync(
        params_template, mesh, sync_axes, mode, bucket_bytes
    )

    def _mean_inexact(tree):
        def leaf(a):
            a = jnp.asarray(a)
            if not jnp.issubdtype(a.dtype, jnp.inexact):
                raise ValueError(
                    "manual comm modes cannot return non-inexact "
                    f"aux/model-state leaves (got {a.dtype}): the "
                    "per-shard value of an integer metric is not the "
                    "global one, and no reduction is universally "
                    "correct (a batch count wants psum, a replicated "
                    "step counter wants identity) -- return it as a "
                    "float, or run comm_mode='flat'"
                )
            return jax.lax.pmean(a, sync_axes)

        return jax.tree.map(leaf, tree)

    def inner(params, ms, batch, rng):
        # The step rng arrives replicated; fold in the shard's linear
        # position so rng-consuming forwards (dropout, noise) draw
        # decorrelated randomness per shard instead of the identical
        # mask on every data shard. Not bit-identical to the flat
        # path's single global-batch draw -- the step-identity pin
        # holds for rng-free forwards (the llama parity tests);
        # rng-consuming models get the training-correct property
        # (independent draws across the batch) in both modes.
        idx = jax.lax.axis_index(sync_axes[0])
        for ax in sync_axes[1:]:
            idx = idx * jax.lax.axis_size(ax) + jax.lax.axis_index(ax)
        rng = jax.random.fold_in(rng, idx)

        def loss_fn(p):
            loss, new_ms, aux = forward(p, ms, batch, rng)
            return loss, (new_ms, aux)

        (loss, (new_ms, aux)), grads = jax.value_and_grad(
            loss_fn, has_aux=True
        )(params)
        grads = sync(grads)
        loss = jax.lax.pmean(loss, sync_axes)
        return (loss, (_mean_inexact(new_ms), _mean_inexact(aux))), grads

    # check_vma=False: loss/grads are replicated by construction (the
    # explicit psum/pmean above IS the ground truth), same rationale
    # as the single-op programs in primitives.py.
    shard_mapped = jax.shard_map(
        inner,
        mesh=mesh,
        in_specs=(P(), P(), batch_pspec, P()),
        out_specs=P(),
        check_vma=False,
    )

    def synced_value_and_grad(params, ms, batch, rng):
        return shard_mapped(params, ms, batch, rng)

    return synced_value_and_grad


# ---------------------------------------------------------------------------
# ppermute-pipelined all-gather and collective-matmul-style gather-matmul
# ---------------------------------------------------------------------------

def ring_all_gather(x, axis: str, n: int):
    """In-``shard_map`` ring all-gather: n-1 neighbor ``ppermute`` hops,
    each hop's transfer overlappable with consuming compute (every hop
    moves only the shard payload, never the gathered whole). Output is
    the tiled gather in combined-axis order, bitwise equal to
    ``jax.lax.all_gather(x, axis, tiled=True)``."""
    if n == 1:
        return x
    me = jax.lax.axis_index(axis)
    perm = [(i, (i + 1) % n) for i in range(n)]
    buf = jnp.zeros((n,) + x.shape, x.dtype)
    buf = jax.lax.dynamic_update_index_in_dim(buf, x, me, axis=0)

    def hop(carry, t):
        buf, cur = carry
        cur = jax.lax.ppermute(cur, axis, perm)
        # After t forward hops this device holds shard (me - t) mod n.
        buf = jax.lax.dynamic_update_index_in_dim(
            buf, cur, (me - t) % n, axis=0
        )
        return (buf, cur), None

    (buf, _), _ = jax.lax.scan(hop, (buf, x), jnp.arange(1, n))
    return buf.reshape((n * x.shape[0],) + x.shape[1:])


def gather_matmul(x, w_shard, axis: str, n: int):
    """In-``shard_map`` collective matmul: ``y = x @ W`` with ``W``
    sharded over ``axis`` on dim 0 (the FSDP layout), computed as a
    ring -- multiply the shard in hand while the next shard's
    ``ppermute`` is in flight. ``x`` is the local activation
    ``[..., K]`` (full contraction dim); ``w_shard`` is ``[K/n, N]``.
    The gathered ``[K, N]`` weight never materializes: peak memory is
    one shard, and each hop hides behind one partial matmul --
    the per-layer FSDP gather overlapped with that layer's compute.
    """
    k_shard = w_shard.shape[0]
    if n == 1:
        return x @ w_shard
    me = jax.lax.axis_index(axis)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def partial(acc, w_cur, t):
        # After t hops the resident shard is (me - t) mod n: contract
        # it against the matching K-slice of x.
        j = (me - t) % n
        xs = jax.lax.dynamic_slice_in_dim(
            x, j * k_shard, k_shard, axis=x.ndim - 1
        )
        return acc + jnp.tensordot(xs, w_cur, axes=((x.ndim - 1,), (0,)))

    acc0 = partial(
        jnp.zeros(x.shape[:-1] + (w_shard.shape[1],),
                  jnp.result_type(x.dtype, w_shard.dtype)),
        w_shard, 0,
    )

    def hop(carry, t):
        acc, w_cur = carry
        w_nxt = jax.lax.ppermute(w_cur, axis, perm)
        # acc uses w_nxt only after the permute lands; the dot for the
        # PREVIOUS shard already issued, so the hop rides behind it.
        return (partial(acc, w_nxt, t), w_nxt), None

    (acc, _), _ = jax.lax.scan(hop, (acc0, w_shard), jnp.arange(1, n))
    return acc


def ppermute_all_gather(mesh: Mesh, axis: str):
    """Standalone jitted ring all-gather program (primitives.py
    convention): input sharded ``P(axis)``, output replicated --
    the benchmark's view of the overlap building block."""
    n = mesh.shape[axis]

    def body(x):
        return ring_all_gather(x, axis, n)

    f = jax.shard_map(
        body, mesh=mesh, in_specs=P(axis), out_specs=P(), check_vma=False
    )
    return jax.jit(f)


def make_pipelined_gather_matmul(mesh: Mesh, axis: str):
    """Standalone jitted collective-matmul program: ``(x, w) -> x @ W``
    with ``x`` batch-sharded and ``w`` dim-0-sharded over ``axis``
    (the FSDP forward shape); output batch-sharded. Lowers to ring
    ``collective-permute`` hops and partial dots -- zero all-gathers
    (pinned by the HLO tests)."""
    n = mesh.shape[axis]

    def body(x, w):
        return gather_matmul(x, w, axis, n)

    f = jax.shard_map(
        body, mesh=mesh, in_specs=(P(axis), P(axis)),
        out_specs=P(axis), check_vma=False,
    )
    return jax.jit(f)
