from tpu_hpc.comm.primitives import (  # noqa: F401
    all_gather,
    all_reduce,
    all_to_all,
    broadcast,
    reduce_scatter,
    ring_shift,
)
from tpu_hpc.comm.hierarchical import (  # noqa: F401
    all_gather_two_phase,
    hier_all_gather,
    hier_all_reduce,
    hier_reduce_scatter,
    psum_two_phase,
    reduce_scatter_two_phase,
)
from tpu_hpc.comm.overlap import (  # noqa: F401
    gather_matmul,
    make_pipelined_gather_matmul,
    make_synced_value_and_grad,
    ppermute_all_gather,
    ring_all_gather,
)
from tpu_hpc.comm.bench import CommBenchmark, run_comm_bench  # noqa: F401
from tpu_hpc.comm.planner import (  # noqa: F401
    CommDecision,
    CostTable,
    Planner,
    TopologyFingerprint,
    fingerprint_devices,
    fingerprint_mesh,
    plan_trainer_grad_sync,
)
