from tpu_hpc.comm.primitives import (  # noqa: F401
    all_gather,
    all_reduce,
    all_to_all,
    broadcast,
    reduce_scatter,
    ring_shift,
)
from tpu_hpc.comm.bench import CommBenchmark, run_comm_bench  # noqa: F401
