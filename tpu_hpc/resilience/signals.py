"""Preemption-notice handling and the run exit-code contract.

TPU-VM spot/maintenance events deliver SIGTERM with a short grace
window (the reference's analogue is PBS resubmission, multinode_ddp_
basic.py:144-155 -- but there the *queue script* owns recovery). The
contract here:

* ``PreemptionGuard`` installs an async-signal-safe flag handler; the
  training loop polls ``guard.triggered`` at chunk boundaries,
  requests one final SYNCHRONOUS checkpoint, and exits cleanly.
* The process then exits with ``EXIT_RESUMABLE`` (75, the sysexits
  EX_TEMPFAIL convention): "nothing is wrong, relaunch me and I will
  resume". The supervisor (supervisor.py) restarts on it without
  treating the run as failing.
* ``EXIT_HANG`` (76) is the hang watchdog's abort code (heartbeat.py):
  the run was killed because it stopped making progress -- restart,
  but count it against the failure budget and keep the diagnostics.

Anything else nonzero is an ordinary crash. Exit codes are the ONLY
channel a dead process has, which is why they are pinned constants
here rather than conventions scattered through launch scripts.

Next to the exit-code contract lives the MORPH channel
(``MorphChannel``): planned topology events -- "slice N goes away in
90 s", "a slice came back" -- are requests to a LIVE process, not
death notices, so they ride a file-based request/ack log instead of a
signal. The elastic coordinator (tpu_hpc.elastic) drains it, quiesces
at a step boundary, and morphs in place; a completed morph burns zero
supervisor budget because no process ever exited.
"""
from __future__ import annotations

import dataclasses
import json
import os
import signal
import sys
import threading
from typing import Iterable, List, Optional, Tuple

# sysexits.h EX_TEMPFAIL: a clean preemption snapshot; relaunch resumes.
EXIT_RESUMABLE = 75
# Hang-watchdog abort: progress stalled; diagnostics were dumped.
EXIT_HANG = 76
# Numeric-health rollback (resilience.guard): poisoned snapshots were
# quarantined and a skip window recorded; the relaunch resumes from the
# last-good checkpoint and fast-forwards the data stream past the
# poisoned batches. Like EXIT_RESUMABLE it means "nothing is wrong
# with the PROCESS, relaunch me" -- but the supervisor counts it
# against a separate rollback budget: an unbounded rollback loop means
# the data (or the model) is poisoned faster than checkpoints land.
EXIT_ROLLBACK = 77

_MEANINGS = {
    0: "success",
    EXIT_RESUMABLE: "resumable (preemption snapshot taken)",
    EXIT_HANG: "hang-watchdog abort (progress stalled)",
    EXIT_ROLLBACK: "guard rollback (resume from last-good snapshot)",
}


def describe_exit(code: int) -> str:
    """Human label for the exit-code contract (supervisor logs)."""
    if code < 0:
        return f"killed by signal {-code}"
    return _MEANINGS.get(code, f"failure (exit {code})")


def exit_code_for(preempted: bool, rolled_back: bool = False) -> int:
    """The code a training entry point should exit with after fit():
    the rollback contract when the numeric-health guard rolled the
    run back (takes precedence -- the supervisor must charge its
    rollback budget, not the free preemption carve-out), the
    resumable contract when the run stopped on a preemption notice,
    plain success otherwise. Usage::

        result = trainer.fit(ds)
        sys.exit(exit_code_for(result.get("preempted", False),
                               result.get("rolled_back", False)))
    """
    if rolled_back:
        return EXIT_ROLLBACK
    return EXIT_RESUMABLE if preempted else 0


def resumable_exit() -> None:
    """Exit now under the resumable contract (snapshot already taken)."""
    sys.exit(EXIT_RESUMABLE)


# Path of the morph request/ack log, exported by whoever schedules
# topology events (supervisor, bench harness) to the process that can
# honor them (the elastic coordinator).
ENV_MORPH_CHANNEL = "TPU_HPC_MORPH_CHANNEL"

# Exported by the elastic coordinator to the Trainers it manages:
# "slice faults are MY job -- your vacuous-pass guard may stand down".
# A Trainer constructed outside the coordinator still hard-rejects an
# armed slice fault (faults.FaultPlan.slice_fault_keys contract).
ENV_ELASTIC_MANAGED = "TPU_HPC_ELASTIC_MANAGED"


@dataclasses.dataclass(frozen=True)
class MorphRequest:
    """One planned topology event on the morph channel.

    ``kind``      "shrink" (a slice is being reclaimed) or "grow" (a
                  slice came back).
    ``n_devices`` the TARGET device count after the event -- the
                  scheduler knows the allocation, the run does not.
    ``step``      earliest step the transition may happen at (the
                  coordinator quiesces at the first chunk boundary
                  with ``step >= this``); 0 means "as soon as legal".
    ``seq``       position in the channel file, assigned by post();
                  acks join on it.
    """

    kind: str
    n_devices: int
    step: int = 0
    seq: int = -1


class MorphChannel:
    """File-based request/ack log for planned topology events.

    Append-only JSONL: requests are ``{"kind", "n_devices", "step"}``
    rows, acks are ``{"ack": seq, ...}`` rows. Appends are O_APPEND
    single-write atomic (same discipline as the heartbeat/supervisor
    logs), so a scheduler posting while the coordinator drains never
    tears a row. The file IS the audit trail: after the run, every
    requested wave and every completed morph is one grep away.
    """

    def __init__(self, path: str):
        self.path = path

    @classmethod
    def from_env(cls, env=None) -> Optional["MorphChannel"]:
        env = os.environ if env is None else env
        path = env.get(ENV_MORPH_CHANNEL, "").strip()
        return cls(path) if path else None

    def _rows(self) -> List[dict]:
        try:
            with open(self.path) as f:
                return [
                    json.loads(line)
                    for line in f if line.strip()
                ]
        except FileNotFoundError:
            return []

    def _append(self, row: dict) -> None:
        d = os.path.dirname(self.path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(self.path, "a") as f:
            f.write(json.dumps(row) + "\n")

    def post(self, kind: str, n_devices: int, step: int = 0) -> int:
        """Schedule a topology event; returns its seq."""
        if kind not in ("shrink", "grow"):
            raise ValueError(
                f"morph kind {kind!r} must be 'shrink' or 'grow'"
            )
        if n_devices < 1:
            raise ValueError(
                f"morph n_devices {n_devices} must be >= 1"
            )
        seq = sum(1 for r in self._rows() if "kind" in r)
        self._append(
            {"kind": kind, "n_devices": int(n_devices),
             "step": int(step), "seq": seq}
        )
        return seq

    def pending(self) -> List[MorphRequest]:
        """Requests not yet acked, in post order."""
        reqs, acked = [], set()
        seq = 0
        for row in self._rows():
            if "ack" in row:
                acked.add(int(row["ack"]))
            elif "kind" in row:
                reqs.append(MorphRequest(
                    kind=row["kind"],
                    n_devices=int(row["n_devices"]),
                    step=int(row.get("step", 0)),
                    seq=seq,
                ))
                seq += 1
        return [r for r in reqs if r.seq not in acked]

    def ack(self, seq: int, **info) -> None:
        """Mark request ``seq`` completed; ``info`` (wire bytes, stall
        seconds, target mesh) rides along for the audit trail."""
        self._append({"ack": int(seq), **info})

    def acked(self) -> List[dict]:
        """The ack rows, in append order (supervisor accounting)."""
        return [r for r in self._rows() if "ack" in r]


class PreemptionGuard:
    """Flag-only signal handler for preemption notices.

    The handler does nothing but set a flag (async-signal-safe: no
    I/O, no locks, no jax) -- the training loop polls ``triggered`` at
    its own safe points. Install/restore are explicit so the guard can
    bracket exactly one fit() and always hand the previous disposition
    back (a dataset/OOM exception mid-loop must not leave the no-op
    flag handler installed for the life of the process).

    Non-main threads cannot install signal handlers; there ``install``
    is a no-op and the guard simply never triggers, matching the old
    inline behavior in Trainer.fit.

    ``flight_reason``: when set, the FIRST notice also dumps the obs
    flight-recorder ring under that reason (best-effort, from the
    handler -- safe because the bus ring lock is reentrant). The
    Trainer leaves this unset and dumps at its own poll point instead;
    the hook exists for embedders whose loop has no safe poll point a
    short grace window is guaranteed to reach.
    """

    def __init__(
        self,
        signums: Iterable[int] = (signal.SIGTERM,),
        flight_reason: Optional[str] = None,
    ):
        self.signums: Tuple[int, ...] = tuple(signums)
        self.flight_reason = flight_reason
        self._event = threading.Event()
        self._old: dict = {}

    @property
    def triggered(self) -> bool:
        return self._event.is_set()

    @property
    def installed(self) -> bool:
        return bool(self._old)

    def _handler(self, signum, frame):
        first = not self._event.is_set()
        self._event.set()
        if first and self.flight_reason is not None:
            try:
                from tpu_hpc.obs import dump_flight

                dump_flight(self.flight_reason)
            except Exception:  # pragma: no cover - diagnostics only
                pass

    def install(self) -> "PreemptionGuard":
        for signum in self.signums:
            try:
                self._old[signum] = signal.signal(signum, self._handler)
            except ValueError:
                # Non-main thread: skip, keep training unguarded.
                pass
        return self

    def restore(self) -> None:
        """Put back the previous dispositions. ``signal.signal``
        returns None when the previous handler was installed from C;
        SIG_DFL is the honest restoration then."""
        for signum, old in self._old.items():
            signal.signal(
                signum, old if old is not None else signal.SIG_DFL
            )
        self._old.clear()

    def __enter__(self) -> "PreemptionGuard":
        return self.install()

    def __exit__(self, *exc) -> Optional[bool]:
        self.restore()
        return None
