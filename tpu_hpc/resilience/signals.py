"""Preemption-notice handling and the run exit-code contract.

TPU-VM spot/maintenance events deliver SIGTERM with a short grace
window (the reference's analogue is PBS resubmission, multinode_ddp_
basic.py:144-155 -- but there the *queue script* owns recovery). The
contract here:

* ``PreemptionGuard`` installs an async-signal-safe flag handler; the
  training loop polls ``guard.triggered`` at chunk boundaries,
  requests one final SYNCHRONOUS checkpoint, and exits cleanly.
* The process then exits with ``EXIT_RESUMABLE`` (75, the sysexits
  EX_TEMPFAIL convention): "nothing is wrong, relaunch me and I will
  resume". The supervisor (supervisor.py) restarts on it without
  treating the run as failing.
* ``EXIT_HANG`` (76) is the hang watchdog's abort code (heartbeat.py):
  the run was killed because it stopped making progress -- restart,
  but count it against the failure budget and keep the diagnostics.

Anything else nonzero is an ordinary crash. Exit codes are the ONLY
channel a dead process has, which is why they are pinned constants
here rather than conventions scattered through launch scripts.
"""
from __future__ import annotations

import signal
import sys
import threading
from typing import Iterable, Optional, Tuple

# sysexits.h EX_TEMPFAIL: a clean preemption snapshot; relaunch resumes.
EXIT_RESUMABLE = 75
# Hang-watchdog abort: progress stalled; diagnostics were dumped.
EXIT_HANG = 76
# Numeric-health rollback (resilience.guard): poisoned snapshots were
# quarantined and a skip window recorded; the relaunch resumes from the
# last-good checkpoint and fast-forwards the data stream past the
# poisoned batches. Like EXIT_RESUMABLE it means "nothing is wrong
# with the PROCESS, relaunch me" -- but the supervisor counts it
# against a separate rollback budget: an unbounded rollback loop means
# the data (or the model) is poisoned faster than checkpoints land.
EXIT_ROLLBACK = 77

_MEANINGS = {
    0: "success",
    EXIT_RESUMABLE: "resumable (preemption snapshot taken)",
    EXIT_HANG: "hang-watchdog abort (progress stalled)",
    EXIT_ROLLBACK: "guard rollback (resume from last-good snapshot)",
}


def describe_exit(code: int) -> str:
    """Human label for the exit-code contract (supervisor logs)."""
    if code < 0:
        return f"killed by signal {-code}"
    return _MEANINGS.get(code, f"failure (exit {code})")


def exit_code_for(preempted: bool, rolled_back: bool = False) -> int:
    """The code a training entry point should exit with after fit():
    the rollback contract when the numeric-health guard rolled the
    run back (takes precedence -- the supervisor must charge its
    rollback budget, not the free preemption carve-out), the
    resumable contract when the run stopped on a preemption notice,
    plain success otherwise. Usage::

        result = trainer.fit(ds)
        sys.exit(exit_code_for(result.get("preempted", False),
                               result.get("rolled_back", False)))
    """
    if rolled_back:
        return EXIT_ROLLBACK
    return EXIT_RESUMABLE if preempted else 0


def resumable_exit() -> None:
    """Exit now under the resumable contract (snapshot already taken)."""
    sys.exit(EXIT_RESUMABLE)


class PreemptionGuard:
    """Flag-only signal handler for preemption notices.

    The handler does nothing but set a flag (async-signal-safe: no
    I/O, no locks, no jax) -- the training loop polls ``triggered`` at
    its own safe points. Install/restore are explicit so the guard can
    bracket exactly one fit() and always hand the previous disposition
    back (a dataset/OOM exception mid-loop must not leave the no-op
    flag handler installed for the life of the process).

    Non-main threads cannot install signal handlers; there ``install``
    is a no-op and the guard simply never triggers, matching the old
    inline behavior in Trainer.fit.

    ``flight_reason``: when set, the FIRST notice also dumps the obs
    flight-recorder ring under that reason (best-effort, from the
    handler -- safe because the bus ring lock is reentrant). The
    Trainer leaves this unset and dumps at its own poll point instead;
    the hook exists for embedders whose loop has no safe poll point a
    short grace window is guaranteed to reach.
    """

    def __init__(
        self,
        signums: Iterable[int] = (signal.SIGTERM,),
        flight_reason: Optional[str] = None,
    ):
        self.signums: Tuple[int, ...] = tuple(signums)
        self.flight_reason = flight_reason
        self._event = threading.Event()
        self._old: dict = {}

    @property
    def triggered(self) -> bool:
        return self._event.is_set()

    @property
    def installed(self) -> bool:
        return bool(self._old)

    def _handler(self, signum, frame):
        first = not self._event.is_set()
        self._event.set()
        if first and self.flight_reason is not None:
            try:
                from tpu_hpc.obs import dump_flight

                dump_flight(self.flight_reason)
            except Exception:  # pragma: no cover - diagnostics only
                pass

    def install(self) -> "PreemptionGuard":
        for signum in self.signums:
            try:
                self._old[signum] = signal.signal(signum, self._handler)
            except ValueError:
                # Non-main thread: skip, keep training unguarded.
                pass
        return self

    def restore(self) -> None:
        """Put back the previous dispositions. ``signal.signal``
        returns None when the previous handler was installed from C;
        SIG_DFL is the honest restoration then."""
        for signum, old in self._old.items():
            signal.signal(
                signum, old if old is not None else signal.SIG_DFL
            )
        self._old.clear()

    def __enter__(self) -> "PreemptionGuard":
        return self.install()

    def __exit__(self, *exc) -> Optional[bool]:
        self.restore()
        return None
