"""Bounded retry with exponential backoff and deterministic jitter.

The generic lever under every transient-failure site in the framework:
``jax.distributed.initialize`` racing slow coordinator startup,
checkpoint restore hitting a flaky shared filesystem, dataset files
not yet visible to a host after rank-0 prepared them (close-to-open
consistency on NFS/GCS). One policy, one place, instead of ad-hoc
sleep loops per call site.

Jitter is DETERMINISTIC given a seed (``random.Random(seed)``, never
the global RNG): restart behavior must be reproducible under the fault
injector, and the bounds are testable -- delay k lies in
``[d_k, d_k * (1 + jitter)]`` with ``d_k = min(base * 2^k, max_delay)``.
Jitter still does its fleet-level job (de-synchronizing N hosts
retrying the same coordinator) because each host seeds with its own
process id by default.
"""
from __future__ import annotations

import os
import random
import time
from typing import Any, Callable, Iterator, Optional, Tuple, Type


def backoff_delays(
    retries: int,
    base_delay: float = 0.5,
    max_delay: float = 30.0,
    jitter: float = 0.5,
    seed: Optional[int] = None,
) -> Iterator[float]:
    """Yield ``retries`` delays: exponential, capped, jittered.

    Delay k is ``d_k * (1 + jitter * u_k)`` with
    ``d_k = min(base_delay * 2^k, max_delay)`` and ``u_k`` uniform in
    [0, 1) from ``random.Random(seed)`` -- so every delay lies in
    ``[d_k, d_k * (1 + jitter)]``. Default seed: this process's pid,
    de-synchronizing hosts that fail in lockstep.
    """
    if retries < 0:
        raise ValueError(f"retries {retries} must be >= 0")
    if base_delay < 0 or max_delay < 0 or jitter < 0:
        raise ValueError(
            f"negative backoff parameter (base {base_delay}, "
            f"max {max_delay}, jitter {jitter})"
        )
    rng = random.Random(os.getpid() if seed is None else seed)
    for k in range(retries):
        d = min(base_delay * (2.0 ** k), max_delay)
        yield d * (1.0 + jitter * rng.random())


def retry_call(
    fn: Callable[..., Any],
    args: Tuple = (),
    kwargs: Optional[dict] = None,
    *,
    retries: int = 3,
    base_delay: float = 0.5,
    max_delay: float = 30.0,
    jitter: float = 0.5,
    retry_on: Tuple[Type[BaseException], ...] = (Exception,),
    on_retry: Optional[Callable[[int, BaseException, float], None]] = None,
    sleep: Callable[[float], None] = time.sleep,
    seed: Optional[int] = None,
    describe: str = "",
) -> Any:
    """Call ``fn(*args, **kwargs)``; on a ``retry_on`` exception, back
    off and try again, up to ``retries`` extra attempts.

    ``on_retry(attempt, exc, delay)`` fires before each backoff sleep
    (logging hook). The final failure re-raises the last exception
    unchanged -- a retry wrapper must never replace the real
    traceback. ``sleep``/``seed`` are injectable for tests.
    """
    kwargs = kwargs or {}
    delays = backoff_delays(
        retries, base_delay, max_delay, jitter, seed=seed
    )
    attempt = 0
    while True:
        try:
            return fn(*args, **kwargs)
        except retry_on as exc:
            attempt += 1
            try:
                delay = next(delays)
            except StopIteration:
                raise exc from None
            if on_retry is not None:
                on_retry(attempt, exc, delay)
            else:
                name = describe or getattr(fn, "__name__", repr(fn))
                print(
                    f"tpu_hpc retry: {name} failed "
                    f"(attempt {attempt}/{retries + 1}: "
                    f"{type(exc).__name__}: {exc}); retrying in "
                    f"{delay:.2f}s",
                    flush=True,
                )
            sleep(delay)


def retrying(**policy) -> Callable[[Callable], Callable]:
    """Decorator form of :func:`retry_call` with a bound policy."""

    def deco(fn: Callable) -> Callable:
        def wrapped(*args, **kwargs):
            return retry_call(fn, args, kwargs, **policy)

        wrapped.__name__ = getattr(fn, "__name__", "wrapped")
        wrapped.__doc__ = fn.__doc__
        return wrapped

    return deco
