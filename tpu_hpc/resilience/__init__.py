"""Resilience: preemption-safe, self-healing training runs.

On TPU pods preemption, coordinator hangs, and flaky slices are the
NORMAL operating regime, not the exception -- every round-5 hardware
run was babysat by an ad-hoc shell watchdog (HW_QUEUE_r05/watchdog.log,
the rc=3 exhausted probe window, the overwritten OOM stash log). This
package moves fault handling from the queue script into the framework,
the position "Collective Communication for 100k+ GPUs" (PAPERS.md)
argues is mandatory at scale:

  signals.py    SIGTERM/preemption-notice guard: final synchronous
                checkpoint + clean exit with a distinct resumable code
  heartbeat.py  step-progress heartbeat file + in-process hang
                watchdog (a stalled collective aborts with diagnostics
                instead of hanging the allocation)
  retry.py      bounded retry/backoff with deterministic jitter, used
                for jax.distributed.initialize, checkpoint restore,
                and shared-filesystem dataset reads
  supervisor.py bounded restart-with-resume process supervisor
                (``python -m tpu_hpc.resilience.supervisor -- <cmd>``)
                replacing the shell watchdog; attempt-unique log
                paths, failure dumps are never overwritten
  faults.py     deterministic fault injection (kill-at-step,
                preempt-at-step, stall, corrupt-ckpt-write) so all of
                the above is testable on CPU

Everything here is stdlib-only and import-cheap: the supervisor must
start (and restart a dead run) without touching jax.
"""
from tpu_hpc.resilience.faults import FaultPlan, fault_plan_from_env  # noqa: F401
from tpu_hpc.resilience.heartbeat import HangWatchdog, Heartbeat  # noqa: F401
from tpu_hpc.resilience.retry import backoff_delays, retry_call  # noqa: F401
from tpu_hpc.resilience.signals import (  # noqa: F401
    EXIT_HANG,
    EXIT_RESUMABLE,
    PreemptionGuard,
    exit_code_for,
)
