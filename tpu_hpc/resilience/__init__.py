"""Resilience: preemption-safe, self-healing training runs.

On TPU pods preemption, coordinator hangs, and flaky slices are the
NORMAL operating regime, not the exception -- every round-5 hardware
run was babysat by an ad-hoc shell watchdog (HW_QUEUE_r05/watchdog.log,
the rc=3 exhausted probe window, the overwritten OOM stash log). This
package moves fault handling from the queue script into the framework,
the position "Collective Communication for 100k+ GPUs" (PAPERS.md)
argues is mandatory at scale:

  signals.py    SIGTERM/preemption-notice guard: final synchronous
                checkpoint + clean exit with a distinct resumable code
  heartbeat.py  step-progress heartbeat file + in-process hang
                watchdog (a stalled collective aborts with diagnostics
                instead of hanging the allocation)
  retry.py      bounded retry/backoff with deterministic jitter, used
                for jax.distributed.initialize, checkpoint restore,
                and shared-filesystem dataset reads
  supervisor.py bounded restart-with-resume process supervisor
                (``python -m tpu_hpc.resilience.supervisor -- <cmd>``)
                replacing the shell watchdog; attempt-unique log
                paths, failure dumps are never overwritten
  faults.py     deterministic fault injection (kill-at-step,
                preempt-at-step, stall, corrupt/bitflip-ckpt-write,
                nan-loss, grad-spike, straggler delay, plus the
                stage-scoped kill/nan/straggler kinds the MPMD
                pipeline runtime consumes) so all of the above is
                testable on CPU
  guard.py      numeric-health guard: per-step health vector
                classification (healthy/spike/poisoned) with
                skip-batch and rollback-to-last-good actions, plus
                the persisted skip windows that fast-forward the
                data stream past poisoned batches

Everything here is stdlib-only and import-cheap: the supervisor must
start (and restart a dead run) without touching jax (guard.py's and
faults.py's jax-touching closures import it lazily).
"""
from tpu_hpc.resilience.faults import FaultPlan, fault_plan_from_env  # noqa: F401
from tpu_hpc.resilience.guard import (  # noqa: F401
    GuardError,
    GuardPolicy,
    StepVerdict,
)
from tpu_hpc.resilience.heartbeat import HangWatchdog, Heartbeat  # noqa: F401
from tpu_hpc.resilience.retry import backoff_delays, retry_call  # noqa: F401
from tpu_hpc.resilience.signals import (  # noqa: F401
    EXIT_HANG,
    EXIT_RESUMABLE,
    EXIT_ROLLBACK,
    PreemptionGuard,
    exit_code_for,
)
