"""Step-progress heartbeat + in-process hang watchdog.

The failure mode this covers is the worst one on a shared pod: a
training process that is neither dead nor progressing -- a wedged
collective, a coordinator that never answers, a host read blocked on a
dead filesystem. The allocation burns until the queue kills it, and
the only artifact is an empty log (the round-5 ad-hoc answer was a
shell `tail`-watching watchdog, HW_QUEUE_r05/watchdog.log).

Two cooperating pieces:

* ``Heartbeat`` -- the trainer atomically rewrites a small JSON file
  (step, wall time, pid, attempt) at every chunk boundary. Outside
  observers (the supervisor, an operator's `cat`) read progress
  without touching the process.
* ``HangWatchdog`` -- a daemon thread INSIDE the process. If the hot
  loop stops ticking for ``timeout_s``, it dumps every thread's stack
  (faulthandler) plus a diagnostic header to ``dump_path`` and aborts
  the process with ``EXIT_HANG`` -- turning an invisible hang into a
  restartable, diagnosable failure. ``os._exit`` is deliberate: a
  wedged XLA runtime cannot be trusted to run atexit handlers.

The timeout must exceed the longest legitimate gap between ticks
(one epoch chunk + one XLA compile on this path); the supervisor's
file-based monitor is the coarser outer layer for the cases where the
whole process (watchdog included) is wedged in C++.
"""
from __future__ import annotations

import faulthandler
import json
import os
import sys
import threading
import time
from typing import Callable, Optional

from tpu_hpc.resilience.signals import EXIT_HANG

ENV_HEARTBEAT = "TPU_HPC_HEARTBEAT"
ENV_HANG_TIMEOUT = "TPU_HPC_HANG_TIMEOUT"
ENV_ATTEMPT = "TPU_HPC_ATTEMPT"


def current_attempt(env=None) -> int:
    """This process's restart ordinal (0 = first launch), exported by
    the supervisor; 0 when running unsupervised."""
    env = os.environ if env is None else env
    try:
        return int(env.get(ENV_ATTEMPT, "0") or 0)
    except ValueError:
        return 0


class Heartbeat:
    """Atomic step-progress file: one JSON object, rewritten in place.

    Write is tmp-file + ``os.replace`` so a reader never sees a torn
    record and a crash mid-tick never corrupts the previous one.
    """

    def __init__(self, path: str, attempt: Optional[int] = None):
        self.path = path
        self.attempt = (
            current_attempt() if attempt is None else int(attempt)
        )
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)

    @classmethod
    def from_env(cls, env=None) -> Optional["Heartbeat"]:
        """The supervisor's contract: it exports ``TPU_HPC_HEARTBEAT``
        and the trainer ticks it; None when unsupervised."""
        env = os.environ if env is None else env
        path = env.get(ENV_HEARTBEAT)
        return cls(path) if path else None

    def tick(self, step: int, **extra) -> None:
        rec = {
            "step": int(step),
            "time": time.time(),
            "pid": os.getpid(),
            "attempt": self.attempt,
            **extra,
        }
        tmp = f"{self.path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(rec, f)
        os.replace(tmp, self.path)

    @staticmethod
    def read(path: str) -> Optional[dict]:
        """Parse a heartbeat file; None if absent or torn (a reader
        must never crash on the file it is monitoring)."""
        try:
            with open(path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None


class HangWatchdog:
    """Daemon thread that aborts the process when progress stalls.

    ``tick()`` from the hot loop resets the clock. If ``timeout_s``
    elapses without a tick, the watchdog writes a diagnostic dump
    (every Python thread's stack via faulthandler -- the wedged
    collective shows up as the main thread parked in an XLA wait) and
    calls ``on_hang`` -- by default ``os._exit(EXIT_HANG)``.

    The dump path is attempt-qualified and opened with ``"x"``-style
    non-clobbering naming: a restart loop must never overwrite the
    evidence of the previous hang (the round-5 overwritten-OOM-log
    lesson, VERDICT item 9).
    """

    def __init__(
        self,
        timeout_s: float,
        *,
        poll_s: Optional[float] = None,
        dump_path: Optional[str] = None,
        on_hang: Optional[Callable[[float], None]] = None,
        exit_code: int = EXIT_HANG,
    ):
        if timeout_s <= 0:
            raise ValueError(f"timeout_s {timeout_s} must be > 0")
        self.timeout_s = float(timeout_s)
        self.poll_s = (
            min(self.timeout_s / 4, 1.0) if poll_s is None else poll_s
        )
        self.dump_path = dump_path
        self.exit_code = exit_code
        self._on_hang = on_hang
        self._last = time.monotonic()
        self._stop = threading.Event()
        self._fired = threading.Event()
        self._thread: Optional[threading.Thread] = None

    @property
    def fired(self) -> bool:
        return self._fired.is_set()

    def tick(self) -> None:
        self._last = time.monotonic()

    def start(self) -> "HangWatchdog":
        self.tick()
        self._thread = threading.Thread(
            target=self._run, name="tpu-hpc-hang-watchdog", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.poll_s * 4)
            self._thread = None

    def _unique_dump_path(self) -> str:
        base = self.dump_path or f"hang.attempt{current_attempt()}.dump"
        path, k = base, 0
        while os.path.exists(path):
            k += 1
            path = f"{base}.{k}"
        return path

    def _dump(self, stalled_s: float) -> Optional[str]:
        try:
            path = self._unique_dump_path()
            parent = os.path.dirname(path)
            if parent:
                os.makedirs(parent, exist_ok=True)
            with open(path, "w") as f:
                f.write(
                    "tpu_hpc hang watchdog: no progress for "
                    f"{stalled_s:.1f}s (timeout {self.timeout_s}s), "
                    f"pid {os.getpid()}, attempt {current_attempt()}; "
                    "all-thread stacks follow\n"
                )
                f.flush()
                faulthandler.dump_traceback(file=f)
            # The stacks say where the process is wedged NOW; the
            # flight-recorder ring says what it was doing on the way
            # there -- dump both. Cross-thread safe: the ring
            # snapshot's lock wait is bounded (EventBus.ring
            # lock_timeout, falling back to a lockless copy), so a
            # main thread wedged mid-emit cannot stop the watchdog
            # from reaching its os._exit.
            try:
                from tpu_hpc.obs import dump_flight

                dump_flight("hang")
            except Exception:  # pragma: no cover - diagnostics only
                pass
            return path
        except OSError:  # pragma: no cover - diagnostics best-effort
            return None

    def _run(self) -> None:
        while not self._stop.wait(self.poll_s):
            stalled = time.monotonic() - self._last
            if stalled < self.timeout_s:
                continue
            self._fired.set()
            path = self._dump(stalled)
            if self._on_hang is not None:
                self._on_hang(stalled)
                return
            print(
                f"tpu_hpc hang watchdog: aborting after {stalled:.1f}s "
                f"without progress (diagnostics: {path})",
                file=sys.stderr, flush=True,
            )
            # A wedged runtime cannot be trusted with a clean
            # interpreter shutdown; exit hard with the contract code.
            os._exit(self.exit_code)
