"""Single-host run supervisor: bounded restart-with-resume.

Replaces the ad-hoc shell watchdogs every round-5 hardware run was
babysat by (HW_QUEUE_r05/watchdog.log) with one auditable process::

    python -m tpu_hpc.resilience.supervisor \
        --max-restarts 3 --log-dir runs/job1 \
        --heartbeat runs/job1/heartbeat.json --heartbeat-timeout 900 \
        -- python train.py --config cfg.yaml

Contract with the child (any command; the Trainer honors all of it
automatically):

* ``TPU_HPC_ATTEMPT`` -- restart ordinal (0-based). Fault injection
  and log naming key off it.
* ``TPU_HPC_HEARTBEAT`` -- exported when ``--heartbeat`` is given; the
  child ticks it (Trainer does, at every chunk boundary). With
  ``--heartbeat-timeout``, a stale file means the child is wedged in a
  way its own in-process watchdog could not catch (e.g. the whole
  interpreter stuck in C++): the supervisor kills and restarts it.
* Exit 0 ends the run. ``EXIT_RESUMABLE`` (75, a clean preemption
  snapshot) restarts WITHOUT consuming the failure budget -- per the
  signals.py contract it means "nothing is wrong, relaunch me".
  ``EXIT_ROLLBACK`` (77, a numeric-health rollback from
  resilience.guard) also restarts without burning the failure budget,
  but against its own ``--max-rollbacks`` bound -- a run that keeps
  poisoning itself must not relaunch forever. Any other nonzero code
  restarts up to ``--max-restarts`` times; every attempt resumes from
  the newest checkpoint via the Trainer's own auto-resume.

Provenance rules (VERDICT item 9 -- the overwritten OOM dump): every
attempt logs to an ATTEMPT-UNIQUE path (``run.attempt<N>.log``; if a
previous supervision left one there, a numeric suffix is added -- a
failure dump is NEVER overwritten), and every attempt appends a JSON
event (rc, duration, log path, restart reason) to
``supervisor.jsonl``.
"""
from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time
from typing import IO, List, Optional, Sequence, Tuple

from tpu_hpc.obs.events import ENV_FLIGHT_DIR, ENV_RUN_ID, gen_run_id
from tpu_hpc.obs.schema import stamp
from tpu_hpc.resilience.heartbeat import ENV_ATTEMPT, ENV_HEARTBEAT
from tpu_hpc.resilience.retry import backoff_delays
from tpu_hpc.resilience.signals import (
    ENV_MORPH_CHANNEL,
    EXIT_HANG,
    EXIT_RESUMABLE,
    EXIT_ROLLBACK,
    MorphChannel,
    describe_exit,
)


def unique_attempt_path(log_dir: str, attempt: int) -> str:
    """``run.attempt<N>.log``, suffixed rather than overwritten when a
    previous supervision already left one in this directory."""
    base = os.path.join(log_dir, f"run.attempt{attempt}.log")
    path, k = base, 0
    while os.path.exists(path):
        k += 1
        path = f"{base}.{k}"
    return path


def _wait_rc(code: int) -> int:
    """Normalize Popen returncodes to shell convention (signal n ->
    128 + n) so the supervisor's own exit code is launcher-readable."""
    return 128 - code if code < 0 else code


class Supervisor:
    def __init__(
        self,
        cmd: Sequence[str],
        *,
        max_restarts: int = 3,
        log_dir: Optional[str] = None,
        heartbeat: Optional[str] = None,
        heartbeat_timeout: float = 0.0,
        backoff: float = 1.0,
        no_restart_on: Sequence[int] = (),
        kill_grace_s: float = 10.0,
        poll_s: float = 0.2,
        max_preemptions: int = 100,
        max_rollbacks: int = 8,
        max_stage_restarts: Optional[int] = None,
    ):
        if not cmd:
            raise ValueError("empty command")
        if max_restarts < 0:
            raise ValueError(f"max_restarts {max_restarts} must be >= 0")
        if max_preemptions < 0:
            raise ValueError(
                f"max_preemptions {max_preemptions} must be >= 0"
            )
        if max_rollbacks < 0:
            raise ValueError(
                f"max_rollbacks {max_rollbacks} must be >= 0"
            )
        if max_stage_restarts is not None and max_stage_restarts < 0:
            raise ValueError(
                f"max_stage_restarts {max_stage_restarts} must be "
                ">= 0"
            )
        self.cmd = list(cmd)
        self.max_restarts = max_restarts
        self.log_dir = log_dir
        self.heartbeat = heartbeat
        self.heartbeat_timeout = heartbeat_timeout
        self.backoff = backoff
        self.max_preemptions = max_preemptions
        self.max_rollbacks = max_rollbacks
        self.max_stage_restarts = max_stage_restarts
        self.no_restart_on = set(no_restart_on)
        self.kill_grace_s = kill_grace_s
        self.poll_s = poll_s
        self._child: Optional[subprocess.Popen] = None
        self._stop_requested = False
        # One run identity across every attempt: exported to each
        # child (TPU_HPC_RUN_ID) and stamped on the supervisor's own
        # events, so attempt logs, the run JSONL, and flight dumps all
        # join on it. An operator-set run id is honored.
        self.run_id = os.environ.get(ENV_RUN_ID) or gen_run_id()
        if log_dir:
            os.makedirs(log_dir, exist_ok=True)
        # Morph-request channel (resilience.signals.MorphChannel): the
        # scheduler-facing sibling of the SIGTERM contract. SIGTERM
        # says "this allocation is going away, snapshot and exit";
        # a morph request says "the topology is CHANGING, transition
        # live". The supervisor owns the channel file next to its logs
        # and exports it to every child so an elastic-managed run
        # (tpu_hpc.elastic.TopologyCoordinator) can consume requests
        # without the supervisor's restart machinery in the loop. An
        # operator-exported channel path is honored as-is.
        self.morph_channel: Optional[MorphChannel] = None
        chan_path = os.environ.get(ENV_MORPH_CHANNEL)
        if chan_path:
            self.morph_channel = MorphChannel(chan_path)
        elif log_dir:
            self.morph_channel = MorphChannel(
                os.path.join(log_dir, "morph_channel.jsonl")
            )
        self._morphs_accounted = 0

    # -- event log ----------------------------------------------------
    def _event(self, **rec) -> None:
        # Schema-stamped like every other telemetry sink
        # (obs/schema.py declares the attempt_* event kinds), so one
        # validator and one report read supervisor.jsonl too.
        rec = stamp(rec, run_id=self.run_id, pid=os.getpid())
        line = json.dumps(rec)
        print(f"supervisor: {line}", file=sys.stderr, flush=True)
        if self.log_dir:
            with open(
                os.path.join(self.log_dir, "supervisor.jsonl"), "a"
            ) as f:
                f.write(line + "\n")

    # -- signal forwarding --------------------------------------------
    def _forward(self, signum, frame):
        """Preemption of the supervisor itself: pass the notice down
        (the child snapshots and exits resumable) and stop
        restarting -- the allocation is going away."""
        self._stop_requested = True
        if self._child is not None and self._child.poll() is None:
            self._child.send_signal(signum)

    # -- heartbeat staleness ------------------------------------------
    def _heartbeat_age(self, attempt_start: float) -> float:
        """Seconds since last observed progress: the heartbeat file's
        mtime, or the attempt start while none exists yet (startup /
        compile time counts against the same budget -- document the
        timeout accordingly)."""
        try:
            return time.time() - os.path.getmtime(self.heartbeat)
        except OSError:
            return time.monotonic() - attempt_start

    def _kill_child(self) -> None:
        assert self._child is not None
        self._child.terminate()
        try:
            self._child.wait(timeout=self.kill_grace_s)
        except subprocess.TimeoutExpired:
            self._child.kill()
            self._child.wait()

    # -- one attempt --------------------------------------------------
    def _run_attempt(self, attempt: int) -> Tuple[int, str, str]:
        """Returns (rc, reason, log_path). ``reason`` is "exit" or
        "heartbeat-stall"."""
        env = dict(os.environ, **{
            ENV_ATTEMPT: str(attempt), ENV_RUN_ID: self.run_id,
        })
        if self.max_stage_restarts is not None:
            # The per-stage budget rides DOWN to the child: an MPMD
            # pipeline run (tpu_hpc.parallel.mpmd) recovers stage
            # failures in-process -- those recoveries never exit, so
            # they can never burn --max-restarts/--max-rollbacks; the
            # exported bound caps how long a flapping stage may keep
            # trying before the child dies with a code the budgets
            # above DO account (StageBudgetExhausted.exit_code).
            env["TPU_HPC_MAX_STAGE_RESTARTS"] = str(
                self.max_stage_restarts
            )
        # Flight-recorder dumps land next to the attempt logs (unless
        # the operator already pointed them elsewhere): the evidence
        # of WHY an attempt died belongs with that attempt's log.
        if self.log_dir and ENV_FLIGHT_DIR not in env:
            env[ENV_FLIGHT_DIR] = self.log_dir
        if (
            self.morph_channel is not None
            and ENV_MORPH_CHANNEL not in env
        ):
            env[ENV_MORPH_CHANNEL] = self.morph_channel.path
        if self.heartbeat:
            env[ENV_HEARTBEAT] = self.heartbeat
            # Clear the previous attempt's heartbeat: a stale file
            # would read as an instant stall and kill every restarted
            # child within one poll, burning the whole budget on one
            # hang. With the file gone, staleness is measured from
            # this attempt's start.
            try:
                os.remove(self.heartbeat)
            except OSError:
                pass
        log_path, log_f = "", None  # type: str, Optional[IO]
        if self.log_dir:
            log_path = unique_attempt_path(self.log_dir, attempt)
            log_f = open(log_path, "w")
        start = time.monotonic()
        try:
            self._child = subprocess.Popen(
                self.cmd,
                stdout=log_f or None,
                stderr=subprocess.STDOUT if log_f else None,
                env=env,
            )
            reason = "exit"
            while True:
                rc = self._child.poll()
                if rc is not None:
                    break
                if (
                    self.heartbeat_timeout > 0
                    and not self._stop_requested
                    and self._heartbeat_age(start)
                    > self.heartbeat_timeout
                ):
                    self._event(
                        event="heartbeat_stall", attempt=attempt,
                        timeout_s=self.heartbeat_timeout,
                    )
                    self._kill_child()
                    # Policy-wise a supervisor-detected stall IS the
                    # watchdog abort, just caught one layer out.
                    rc, reason = EXIT_HANG, "heartbeat-stall"
                    break
                time.sleep(self.poll_s)
            return _wait_rc(rc), reason, log_path
        finally:
            self._child = None
            if log_f:
                log_f.close()

    # -- morph accounting ---------------------------------------------
    def _account_morphs(self, attempt: int) -> None:
        """Book completed live topology morphs as ZERO budget burned.
        A morph acked on the channel means the child transitioned
        in-process -- no exit, no relaunch -- so by construction it
        cannot have consumed the restart, preemption, or rollback
        budgets. The ``morphs_complete`` event makes that accounting
        auditable next to the attempt_* rows it would otherwise be
        conflated with."""
        if self.morph_channel is None:
            return
        try:
            acked = self.morph_channel.acked()
        except (OSError, ValueError):
            return
        fresh = len(acked) - self._morphs_accounted
        if fresh <= 0:
            return
        self._morphs_accounted = len(acked)
        self._event(
            event="morphs_complete", attempt=attempt, count=fresh,
            budget_burned=0,
        )

    def _surface_rollup(self, attempt: int) -> None:
        """Between attempts, surface what the live digest channels say
        (obs/live.py): the fleet scoreboard on stderr next to the
        attempt rows -- which host/stage was the straggler, who went
        silent -- plus one schema-stamped ``digest_stale`` record per
        publisher whose feed stopped, so the restart decision's
        context rides supervisor.jsonl. Diagnostics: every failure is
        swallowed (the dump_flight contract -- surfacing telemetry
        must never turn a restart loop into a new crash)."""
        from tpu_hpc.obs.digest import ENV_DIGEST_DIR

        digest_dir = os.environ.get(ENV_DIGEST_DIR)
        if not digest_dir:
            return
        try:
            from tpu_hpc.obs.live import (
                format_scoreboard,
                rollup_from_dir,
                stale_entries,
            )

            view = rollup_from_dir(digest_dir).build()
            if not view["sources"]:
                return
            for line in format_scoreboard(view).splitlines():
                print(f"supervisor: {line}", file=sys.stderr)
            sys.stderr.flush()
            for e in stale_entries(view):
                self._event(
                    event="digest_stale", attempt=attempt, **e
                )
        except Exception:
            return

    # -- the loop -----------------------------------------------------
    def run(self) -> int:
        old = {}
        for signum in (signal.SIGTERM, signal.SIGINT):
            try:
                old[signum] = signal.signal(signum, self._forward)
            except ValueError:  # non-main thread (tests)
                pass
        # seed=None -> pid-seeded jitter: one supervisor per pod
        # worker must NOT relaunch all ranks in lockstep after a
        # pod-wide fault (the thundering-herd knock the jitter
        # exists to break up).
        delays = backoff_delays(
            self.max_restarts, base_delay=self.backoff,
            max_delay=60.0, jitter=0.25, seed=None,
        )
        try:
            attempt = 0
            failures = 0
            preemptions = 0
            rollbacks = 0
            while True:
                self._event(
                    event="attempt_start", attempt=attempt,
                    cmd=self.cmd,
                )
                t0 = time.monotonic()
                rc, reason, log_path = self._run_attempt(attempt)
                self._event(
                    event="attempt_end", attempt=attempt, rc=rc,
                    meaning=describe_exit(rc), reason=reason,
                    duration_s=round(time.monotonic() - t0, 3),
                    log=log_path,
                )
                self._account_morphs(attempt)
                self._surface_rollup(attempt)
                if rc == 0:
                    return 0
                if self._stop_requested:
                    # Preemption rode through us: propagate the
                    # child's (resumable) code to the launcher above.
                    return rc
                if rc in self.no_restart_on:
                    self._event(
                        event="giving_up", attempt=attempt, rc=rc,
                        why="exit code marked non-restartable",
                    )
                    return rc
                if rc == EXIT_RESUMABLE:
                    # Clean preemption snapshot: "nothing is wrong,
                    # relaunch me" (signals.py contract) -- restart
                    # WITHOUT burning the failure budget or the
                    # escalating backoff (a spot run preempted
                    # max_restarts+1 times must not be abandoned
                    # while healthy). Separately GENEROUSLY bounded:
                    # a preemption cadence faster than the child's
                    # checkpoint cadence makes zero progress per
                    # attempt, and an unbounded loop would burn the
                    # allocation forever.
                    if preemptions >= self.max_preemptions:
                        self._event(
                            event="giving_up", attempt=attempt, rc=rc,
                            why=f"preemption budget "
                            f"({self.max_preemptions}) exhausted -- "
                            "preemption cadence may be outpacing "
                            "checkpoint cadence",
                        )
                        return rc
                    preemptions += 1
                    self._event(
                        event="restarting", next_attempt=attempt + 1,
                        backoff_s=round(self.backoff, 3),
                        why="resumable preemption snapshot",
                    )
                    time.sleep(self.backoff)
                    if self._stop_requested:
                        return rc
                    attempt += 1
                    continue
                if rc == EXIT_ROLLBACK:
                    # Numeric-health rollback (resilience.guard): the
                    # child quarantined poisoned snapshots, recorded a
                    # skip window, and asked to be relaunched from the
                    # last-good checkpoint. Healthy-process exits, so
                    # they never burn the failure budget -- but they
                    # get their OWN bound, distinct from both the
                    # restart and the preemption budgets: repeated
                    # rollbacks mean the run poisons itself faster
                    # than checkpoints land (bad data shard, diverging
                    # model), and relaunching forever just burns the
                    # allocation re-training the same span.
                    if rollbacks >= self.max_rollbacks:
                        self._event(
                            event="giving_up", attempt=attempt, rc=rc,
                            why=f"rollback budget "
                            f"({self.max_rollbacks}) exhausted -- the "
                            "run keeps hitting numeric anomalies "
                            "faster than it checkpoints past them",
                        )
                        return rc
                    rollbacks += 1
                    self._event(
                        event="restarting", next_attempt=attempt + 1,
                        backoff_s=round(self.backoff, 3),
                        why="guard rollback to last-good snapshot",
                    )
                    time.sleep(self.backoff)
                    if self._stop_requested:
                        return rc
                    attempt += 1
                    continue
                if failures >= self.max_restarts:
                    self._event(
                        event="giving_up", attempt=attempt, rc=rc,
                        why=f"restart budget ({self.max_restarts}) "
                        "exhausted",
                    )
                    return rc
                failures += 1
                delay = next(delays)
                self._event(
                    event="restarting", next_attempt=attempt + 1,
                    backoff_s=round(delay, 3),
                )
                time.sleep(delay)
                if self._stop_requested:
                    # Preemption arrived during the backoff sleep
                    # (no child to forward to): launching another
                    # attempt would strand a snapshot-less child in a
                    # dying allocation.
                    return rc
                attempt += 1
        finally:
            for signum, handler in old.items():
                # signal.signal returns None when the previous handler
                # was installed from C; SIG_DFL is the honest
                # restoration then (same edge PreemptionGuard handles).
                signal.signal(
                    signum,
                    handler if handler is not None else signal.SIG_DFL,
                )


def run_supervised(cmd: Sequence[str], **kwargs) -> int:
    """Library entry point (bench.py/tpu_hpc.serve --supervise use
    this)."""
    return Supervisor(cmd, **kwargs).run()


def strip_flag(argv: Sequence[str], flag: str) -> List[str]:
    """Remove ``flag N`` / ``flag=N`` from an argv copy -- the shared
    re-exec helper for CLIs that wrap themselves in the supervisor
    (bench.py --supervise, tpu_hpc.serve --supervise): the supervised
    child must run the program itself, and a surviving flag would
    recurse supervisors forever."""
    out: List[str] = []
    skip = False
    for a in argv:
        if skip:
            skip = False
            continue
        if a == flag:
            skip = True
            continue
        if a.startswith(flag + "="):
            continue
        out.append(a)
    return out


def _split_argv(
    argv: Sequence[str],
) -> Tuple[List[str], List[str]]:
    if "--" not in argv:
        raise SystemExit(
            "usage: python -m tpu_hpc.resilience.supervisor "
            "[options] -- <command> [args...]   (the '--' is required)"
        )
    i = list(argv).index("--")
    return list(argv[:i]), list(argv[i + 1:])


def main(argv: Optional[Sequence[str]] = None) -> int:
    argv = sys.argv[1:] if argv is None else list(argv)
    opts, cmd = _split_argv(argv)
    ap = argparse.ArgumentParser(
        prog="tpu_hpc.resilience.supervisor",
        description="bounded restart-with-resume run supervisor",
    )
    ap.add_argument("--max-restarts", type=int, default=3)
    ap.add_argument(
        "--log-dir", type=str, default=None,
        help="attempt-unique child logs + supervisor.jsonl here "
        "(default: inherit the supervisor's stdio)",
    )
    ap.add_argument(
        "--heartbeat", type=str, default=None,
        help="heartbeat file path exported to the child as "
        f"{ENV_HEARTBEAT}",
    )
    ap.add_argument(
        "--heartbeat-timeout", type=float, default=0.0,
        help="seconds of heartbeat staleness before the child is "
        "killed and restarted (0 = off); must cover startup + one "
        "epoch chunk + one XLA compile",
    )
    ap.add_argument("--backoff", type=float, default=1.0)
    ap.add_argument(
        "--max-preemptions", type=int, default=100,
        help="separate generous bound on EXIT_RESUMABLE (75) "
        "preemption restarts (they never burn --max-restarts); "
        "exhausting it usually means preemptions outpace checkpoints",
    )
    ap.add_argument(
        "--max-rollbacks", type=int, default=8,
        help="separate bound on EXIT_ROLLBACK (77) numeric-health "
        "rollback restarts (resilience.guard; they never burn "
        "--max-restarts); exhausting it means the run keeps "
        "poisoning itself faster than it checkpoints past the bad "
        "spans",
    )
    ap.add_argument(
        "--max-stage-restarts", type=int, default=None,
        help="per-STAGE restart budget exported to the child as "
        "TPU_HPC_MAX_STAGE_RESTARTS (MPMD pipeline runs, "
        "tpu_hpc.parallel.mpmd): stage-local recoveries happen "
        "inside the child and never burn --max-restarts/"
        "--max-rollbacks; this bounds how often any ONE stage may "
        "restart before the child gives up with a budget-accounted "
        "exit (default: the child's own default, 3)",
    )
    ap.add_argument(
        "--no-restart-on", type=str, default="",
        help="comma-separated exit codes that end the run immediately "
        "(e.g. '2' for usage errors)",
    )
    args = ap.parse_args(opts)
    if not cmd:
        ap.error("no command after '--'")
    no_restart = tuple(
        int(c) for c in args.no_restart_on.split(",") if c.strip()
    )
    if args.heartbeat_timeout > 0 and not args.heartbeat:
        ap.error("--heartbeat-timeout requires --heartbeat")
    return run_supervised(
        cmd,
        max_restarts=args.max_restarts,
        log_dir=args.log_dir,
        heartbeat=args.heartbeat,
        heartbeat_timeout=args.heartbeat_timeout,
        backoff=args.backoff,
        no_restart_on=no_restart,
        max_preemptions=args.max_preemptions,
        max_rollbacks=args.max_rollbacks,
        max_stage_restarts=args.max_stage_restarts,
    )


if __name__ == "__main__":
    sys.exit(main())
