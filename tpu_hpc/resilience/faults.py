"""Deterministic fault injection: make failures a test input.

Every resilience mechanism in this package claims to survive a failure
class; none of those claims are testable on CPU unless the failure can
be produced on demand, at an exact step, on an exact attempt. This
module is that switch. It is env-var armed (``TPU_HPC_FAULTS``) so the
injected process needs NO code changes -- the supervisor test launches
the ordinary training entry point with::

    TPU_HPC_FAULTS="kill_at_step=4" \
        python -m tpu_hpc.resilience.supervisor -- python train.py ...

Fault kinds (all step-indexed, fired at the trainer's chunk
boundaries, i.e. at the first progress point where ``step >= N``):

* ``kill_at_step=N``     SIGKILL self -- a hard preemption/OOM kill,
                         no grace, no snapshot.
* ``preempt_at_step=N``  SIGTERM self -- a graceful preemption notice;
                         exercises PreemptionGuard + emergency save.
* ``stall_at_step=N``    sleep ``stall_s`` (default 3600) -- a wedged
                         collective; exercises the hang watchdog.
* ``corrupt_ckpt_at_step=N``  garbage every file of checkpoint step N
                         after it lands -- a torn write; exercises
                         restore fallback to the previous step.
* ``bitflip_ckpt_at_step=N``  flip ONE BIT in one tensor of checkpoint
                         step N, rewritten through orbax so every file
                         stays parseable -- a silent data corruption
                         (SDC) the torn-write fallback cannot see;
                         exercises ckpt.integrity checksum
                         verification + quarantine.
* ``nan_loss_at_step=N`` force the jitted step's loss AND gradients
                         non-finite when the DATA INDEX equals N (a
                         poisoned batch); exercises the numeric-health
                         guard's skip / rollback-to-last-good paths.
                         Keyed on the data index, not the step, so a
                         rollback that really fast-forwards past the
                         poisoned batch never re-hits it.
* ``grad_spike_at_step=N`` (scale ``grad_spike_scale``, default 1e4)
                         multiply the step's gradients at data index N
                         -- a loss spike; exercises the guard's
                         rolling-median spike detection.
* ``straggler_ms=F``     sleep F ms inside every metered chunk (from
                         ``straggler_at_step``, default 0) -- a
                         degraded host; exercises the stall watermark.

Stage-scoped faults (consumed ONLY by the MPMD pipeline runtime,
``tpu_hpc.parallel.mpmd`` -- the SPMD Trainer hard-rejects them at
construction so a stage fault on a non-MPMD run fails loudly instead
of passing a chaos test vacuously):

* ``stage_kill_at=<stage>:<step>``  kill that stage's worker
                         MID-STEP (at its last forward dispatch of
                         that step, every microbatch in flight) -- a
                         preempted slice / crashed host; exercises
                         per-stage crash detection, stage-local
                         restart, and in-flight microbatch replay.
* ``stage_nan_at=<stage>:<step>``   poison that stage's forward
                         output at that step (one-shot -- a transient
                         SDC on the stage's chips); exercises the
                         per-stage guard path: poisoned verdict,
                         stage-local rollback, recorded window.
* ``stage_straggler=<stage>:<factor>``  multiply that stage's op cost
                         by ``factor`` on the runtime's virtual
                         clock -- a thermally-degraded slice;
                         exercises cross-stage slow detection and the
                         bubble telemetry.

Slice-scoped faults (consumed ONLY by topology-aware runtimes -- the
elastic coordinator, ``tpu_hpc.elastic``, and the MPMD pipeline; a
plain SPMD Trainer hard-rejects them at construction unless it is
running UNDER the coordinator, so a slice fault on a run that cannot
morph fails loudly instead of passing a chaos test vacuously):

* ``slice_down_at_step=N``  a planned slice loss at the first
                         progress point where ``step >= N``: the
                         coordinator quiesces at the step boundary and
                         morphs onto the surviving device set; the
                         MPMD runtime remaps the lost stage onto
                         surviving devices WITHOUT burning its restart
                         budget.
* ``slice_up_at_step=N``    the wave recedes: a slice returns at
                         ``step >= N`` and the run grows back onto the
                         full device set (same quiesce-morph-resume
                         path, in reverse).

``on_attempt`` (default 0) scopes injection to one restart ordinal so
a supervised run fails once and then completes -- the
restart-with-resume round trip, deterministic end to end.
``on_attempt=-1`` arms the fault on EVERY attempt: the guard's
rollback proof uses it so the only way the relaunch survives is by
actually skipping the poisoned data index.
"""
from __future__ import annotations

import dataclasses
import os
import signal
import time
from typing import Optional

from tpu_hpc.resilience.heartbeat import current_attempt

ENV_FAULTS = "TPU_HPC_FAULTS"

_INT_KEYS = (
    "kill_at_step",
    "preempt_at_step",
    "stall_at_step",
    "corrupt_ckpt_at_step",
    "bitflip_ckpt_at_step",
    "nan_loss_at_step",
    "grad_spike_at_step",
    "straggler_at_step",
    "slice_down_at_step",
    "slice_up_at_step",
    "on_attempt",
)

_FLOAT_KEYS = (
    "stall_s",
    "grad_spike_scale",
    "straggler_ms",
)

# Stage-scoped fault keys (MPMD pipeline runtime only): composite
# "<stage>:<value>" specs, parsed with their own typed casts.
STAGE_FAULT_KEYS = (
    "stage_kill_at",
    "stage_nan_at",
    "stage_straggler",
)

# Slice-scoped fault keys: planned topology events only a
# morph-capable runtime (tpu_hpc.elastic coordinator, MPMD pipeline)
# can honor. Plain int steps, so _INT_KEYS carries the casts.
SLICE_FAULT_KEYS = (
    "slice_down_at_step",
    "slice_up_at_step",
)


def _stage_step(v: str) -> "tuple[int, int]":
    sid, sep, at = v.partition(":")
    if not sep:
        raise ValueError(v)
    i, n = int(sid), int(at)
    if i < 0 or n < 0:
        raise ValueError(v)
    return (i, n)


def _stage_factor(v: str) -> "tuple[int, float]":
    sid, sep, factor = v.partition(":")
    if not sep:
        raise ValueError(v)
    i, f = int(sid), float(factor)
    if i < 0 or f <= 0:
        raise ValueError(v)
    return (i, f)


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    """A parsed, armed fault schedule for THIS process."""

    kill_at_step: Optional[int] = None
    preempt_at_step: Optional[int] = None
    stall_at_step: Optional[int] = None
    corrupt_ckpt_at_step: Optional[int] = None
    bitflip_ckpt_at_step: Optional[int] = None
    nan_loss_at_step: Optional[int] = None
    grad_spike_at_step: Optional[int] = None
    grad_spike_scale: float = 1e4
    straggler_ms: float = 0.0
    straggler_at_step: int = 0
    stall_s: float = 3600.0
    # Stage-scoped (MPMD runtime only; the SPMD Trainer rejects armed
    # stage faults at construction -- see stage_fault_keys).
    stage_kill_at: Optional[tuple] = None     # (stage, step)
    stage_nan_at: Optional[tuple] = None      # (stage, step)
    stage_straggler: Optional[tuple] = None   # (stage, factor)
    # Slice-scoped (morph-capable runtimes only -- see
    # slice_fault_keys for the vacuous-pass guard contract).
    slice_down_at_step: Optional[int] = None
    slice_up_at_step: Optional[int] = None
    on_attempt: int = 0
    attempt: int = 0
    # Telemetry one-shot latch (mutable contents are legal on a
    # frozen dataclass): ``step >= N`` keeps re-matching on every
    # later progress point, and a re-fired stall must re-sleep -- but
    # it must NOT re-emit a fault event per chunk, flooding the ring
    # and the report's fault list.
    _announced: set = dataclasses.field(
        default_factory=set, compare=False, repr=False,
    )

    @property
    def active(self) -> bool:
        """Injection is scoped to one restart ordinal: the fault fires
        once, and the relaunched attempt runs clean. ``on_attempt=-1``
        arms every attempt (the rollback proofs need the poison to
        persist across the relaunch)."""
        return self.on_attempt == -1 or self.attempt == self.on_attempt

    def _announce(self, kind: str, step: int, dump: bool) -> None:
        """Record the injection in the telemetry spine: a ``fault``
        event into the bus ring (so post-hoc forensics see the cause
        next to its effects), plus -- for faults the process will not
        survive -- a flight-recorder dump NOW, while there is still a
        process to write it. Best-effort: injection must fire even if
        telemetry is broken (that may be what's under test). One
        event per fault kind, however often the ``step >= N`` match
        re-fires."""
        if kind in self._announced:
            return
        self._announced.add(kind)
        try:
            from tpu_hpc.obs import dump_flight, get_bus

            get_bus().emit("fault", kind=kind, step=step)
            if dump:
                dump_flight(f"fault_{kind}")
        except Exception:  # pragma: no cover - diagnostics only
            pass

    def on_step(self, step: int) -> None:
        """Called from the training loop at each progress point."""
        if not self.active:
            return
        if (
            self.stall_at_step is not None
            and step >= self.stall_at_step
        ):
            # No dump here: the hang watchdog dumps when it fires --
            # that's the mechanism under test.
            self._announce("stall", step, dump=False)
            time.sleep(self.stall_s)
        if (
            self.preempt_at_step is not None
            and step >= self.preempt_at_step
        ):
            # Graceful notice to self: PreemptionGuard's flag is set
            # synchronously (same-process SIGTERM runs the Python
            # handler at the next bytecode boundary). The graceful
            # path dumps at the Trainer's poll point.
            self._announce("preempt", step, dump=False)
            os.kill(os.getpid(), signal.SIGTERM)
        if self.kill_at_step is not None and step >= self.kill_at_step:
            # SIGKILL gives no grace at all -- dump the ring first;
            # this IS the "what was it doing right before it died"
            # artifact a hard preemption otherwise destroys.
            self._announce("kill", step, dump=True)
            os.kill(os.getpid(), signal.SIGKILL)

    def maybe_straggle(self, step: int) -> None:
        """Per-chunk host delay (``straggler_ms``, from
        ``straggler_at_step``): the trainer calls this INSIDE its
        metered window so the injected slowness is visible to the
        stall watermark, exactly like a thermally-throttling host."""
        if (
            not self.active
            or self.straggler_ms <= 0
            or step < self.straggler_at_step
        ):
            return
        self._announce("straggler", step, dump=False)
        time.sleep(self.straggler_ms / 1000.0)

    def numeric_fault_fn(self):
        """A ``(data_index, loss, grads) -> (loss, grads)`` closure
        perturbing the jitted training step, or None when no numeric
        fault is armed. Keyed on the DATA index (``step + skip-window
        offset``), so a guard rollback that fast-forwards the stream
        past the poisoned batch genuinely never re-hits it -- the
        end-to-end proof that the skip window works.

        jax is imported inside the closure: this module must stay
        import-cheap for the supervisor (package contract)."""
        if not self.active or (
            self.nan_loss_at_step is None
            and self.grad_spike_at_step is None
        ):
            return None
        if self.nan_loss_at_step is not None:
            self._announce("nan_loss", self.nan_loss_at_step, dump=False)
        if self.grad_spike_at_step is not None:
            self._announce(
                "grad_spike", self.grad_spike_at_step, dump=False
            )
        nan_at = self.nan_loss_at_step
        spike_at = self.grad_spike_at_step
        spike_scale = self.grad_spike_scale

        def apply(data_index, loss, grads):
            import jax
            import jax.numpy as jnp

            if nan_at is not None:
                bad = data_index == nan_at
                loss = jnp.where(bad, jnp.nan, loss)
                grads = jax.tree.map(
                    lambda g: jnp.where(
                        bad, jnp.asarray(jnp.nan, g.dtype), g
                    ),
                    grads,
                )
            if spike_at is not None:
                scale = jnp.where(
                    data_index == spike_at, spike_scale, 1.0
                )
                grads = jax.tree.map(
                    lambda g: g * scale.astype(g.dtype), grads
                )
            return loss, grads

        return apply

    def stage_fault_keys(self) -> "list[str]":
        """The armed stage-scoped fault keys. Consumers that are NOT
        the MPMD pipeline runtime must hard-reject a plan where this
        is non-empty: a stage fault silently injecting nothing makes
        the chaos test pass vacuously (the loadgen fleet-fault
        discipline, applied to training)."""
        return [
            k for k in STAGE_FAULT_KEYS
            if getattr(self, k) is not None
        ]

    def slice_fault_keys(self) -> "list[str]":
        """The armed slice-scoped fault keys. Same vacuous-pass
        contract as :meth:`stage_fault_keys`: a runtime that cannot
        morph its topology (a plain SPMD Trainer outside the elastic
        coordinator) must hard-reject a plan where this is non-empty,
        and a morph-capable runtime must hard-fail a run where an
        armed slice fault never got the chance to fire."""
        return [
            k for k in SLICE_FAULT_KEYS
            if getattr(self, k) is not None
        ]

    def wants_ckpt_corruption(self, step: int) -> bool:
        return self.active and self.corrupt_ckpt_at_step == step

    def wants_ckpt_bitflip(self, step: int) -> bool:
        """Silent-corruption schedule: the actual flip lives in
        ckpt.CheckpointManager (it needs orbax to rewrite the step
        parseably); this module only owns WHEN."""
        return self.active and self.bitflip_ckpt_at_step == step

    def announce_bitflip(self, step: int) -> None:
        self._announce("bitflip_ckpt", step, dump=False)

    def corrupt_checkpoint(self, step_dir: str) -> int:
        """Garbage every regular file under ``step_dir`` (a torn
        multi-file write); returns the count corrupted."""
        n = 0
        for root, _, files in os.walk(step_dir):
            for name in files:
                corrupt_file(os.path.join(root, name))
                n += 1
        return n


def corrupt_file(path: str) -> None:
    """Deterministically destroy a file's contents in place (replace
    with a short garbage header -- breaks zarr/msgpack/json parsing
    alike)."""
    with open(path, "wb") as f:
        f.write(b"\x00TPU_HPC_FAULT_CORRUPTED\x00")


def parse_kv_spec(spec: str, env_name: str, casts) -> dict:
    """Parse a ``"key=value,key=value"`` fault/config spec with the
    typed-error discipline every injection spec in this repo follows:

    * unknown keys are a hard error naming the key, the FULL spec and
      the known-key set -- a typo'd key silently injecting nothing
      makes a chaos test pass vacuously;
    * malformed values are a hard error naming the key, the spec and
      the expected type -- a bare ``int()`` traceback would point at
      the parser instead of the operator's typo;
    * duplicate keys are last-wins, like the env vars they ride in on.

    ``casts`` maps each known key to ``(cast_fn, expected_kind)``;
    ``cast_fn`` raising ``ValueError`` marks the value malformed (so
    range checks belong inside the cast). Returns ``{key: parsed}``
    for the keys present. The one parse loop shared by
    ``TPU_HPC_FAULTS`` (this module) and ``TPU_HPC_LOADGEN_FAULTS``
    (tpu_hpc/loadgen/harness.py) -- the disciplines must not fork.
    """
    fields: dict = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        key, _, val = part.partition("=")
        key = key.strip()
        if key not in casts:
            raise ValueError(
                f"unknown fault key {key!r} in {env_name}={spec!r} "
                f"(known: {', '.join(sorted(casts))})"
            )
        cast, kind = casts[key]
        try:
            fields[key] = cast(val.strip())
        except ValueError:
            raise ValueError(
                f"invalid value {val.strip()!r} for fault key "
                f"{key!r} in {env_name}={spec!r}: expected {kind}"
            ) from None
    return fields


def fault_plan_from_env(env=None) -> Optional[FaultPlan]:
    """Parse ``TPU_HPC_FAULTS`` ("k=v,k=v"); None when unset (the
    production default -- every injection site is a no-op). The
    unknown-key / malformed-value discipline lives in
    :func:`parse_kv_spec`.
    """
    env = os.environ if env is None else env
    spec = env.get(ENV_FAULTS, "").strip()
    if not spec:
        return None
    casts = {
        **{k: (int, "an integer") for k in _INT_KEYS},
        **{k: (float, "a number") for k in _FLOAT_KEYS},
        "stage_kill_at": (
            _stage_step, "'<stage>:<step>' (non-negative ints)",
        ),
        "stage_nan_at": (
            _stage_step, "'<stage>:<step>' (non-negative ints)",
        ),
        "stage_straggler": (
            _stage_factor,
            "'<stage>:<factor>' (non-negative int : factor > 0)",
        ),
    }
    fields = parse_kv_spec(spec, ENV_FAULTS, casts)
    return FaultPlan(attempt=current_attempt(env), **fields)
