"""Deterministic fault injection: make failures a test input.

Every resilience mechanism in this package claims to survive a failure
class; none of those claims are testable on CPU unless the failure can
be produced on demand, at an exact step, on an exact attempt. This
module is that switch. It is env-var armed (``TPU_HPC_FAULTS``) so the
injected process needs NO code changes -- the supervisor test launches
the ordinary training entry point with::

    TPU_HPC_FAULTS="kill_at_step=4" \
        python -m tpu_hpc.resilience.supervisor -- python train.py ...

Fault kinds (all step-indexed, fired at the trainer's chunk
boundaries, i.e. at the first progress point where ``step >= N``):

* ``kill_at_step=N``     SIGKILL self -- a hard preemption/OOM kill,
                         no grace, no snapshot.
* ``preempt_at_step=N``  SIGTERM self -- a graceful preemption notice;
                         exercises PreemptionGuard + emergency save.
* ``stall_at_step=N``    sleep ``stall_s`` (default 3600) -- a wedged
                         collective; exercises the hang watchdog.
* ``corrupt_ckpt_at_step=N``  garbage every file of checkpoint step N
                         after it lands -- a torn write; exercises
                         restore fallback to the previous step.

``on_attempt`` (default 0) scopes injection to one restart ordinal so
a supervised run fails once and then completes -- the
restart-with-resume round trip, deterministic end to end.
"""
from __future__ import annotations

import dataclasses
import os
import signal
import time
from typing import Optional

from tpu_hpc.resilience.heartbeat import current_attempt

ENV_FAULTS = "TPU_HPC_FAULTS"

_INT_KEYS = (
    "kill_at_step",
    "preempt_at_step",
    "stall_at_step",
    "corrupt_ckpt_at_step",
    "on_attempt",
)


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    """A parsed, armed fault schedule for THIS process."""

    kill_at_step: Optional[int] = None
    preempt_at_step: Optional[int] = None
    stall_at_step: Optional[int] = None
    corrupt_ckpt_at_step: Optional[int] = None
    stall_s: float = 3600.0
    on_attempt: int = 0
    attempt: int = 0
    # Telemetry one-shot latch (mutable contents are legal on a
    # frozen dataclass): ``step >= N`` keeps re-matching on every
    # later progress point, and a re-fired stall must re-sleep -- but
    # it must NOT re-emit a fault event per chunk, flooding the ring
    # and the report's fault list.
    _announced: set = dataclasses.field(
        default_factory=set, compare=False, repr=False,
    )

    @property
    def active(self) -> bool:
        """Injection is scoped to one restart ordinal: the fault fires
        once, and the relaunched attempt runs clean."""
        return self.attempt == self.on_attempt

    def _announce(self, kind: str, step: int, dump: bool) -> None:
        """Record the injection in the telemetry spine: a ``fault``
        event into the bus ring (so post-hoc forensics see the cause
        next to its effects), plus -- for faults the process will not
        survive -- a flight-recorder dump NOW, while there is still a
        process to write it. Best-effort: injection must fire even if
        telemetry is broken (that may be what's under test). One
        event per fault kind, however often the ``step >= N`` match
        re-fires."""
        if kind in self._announced:
            return
        self._announced.add(kind)
        try:
            from tpu_hpc.obs import dump_flight, get_bus

            get_bus().emit("fault", kind=kind, step=step)
            if dump:
                dump_flight(f"fault_{kind}")
        except Exception:  # pragma: no cover - diagnostics only
            pass

    def on_step(self, step: int) -> None:
        """Called from the training loop at each progress point."""
        if not self.active:
            return
        if (
            self.stall_at_step is not None
            and step >= self.stall_at_step
        ):
            # No dump here: the hang watchdog dumps when it fires --
            # that's the mechanism under test.
            self._announce("stall", step, dump=False)
            time.sleep(self.stall_s)
        if (
            self.preempt_at_step is not None
            and step >= self.preempt_at_step
        ):
            # Graceful notice to self: PreemptionGuard's flag is set
            # synchronously (same-process SIGTERM runs the Python
            # handler at the next bytecode boundary). The graceful
            # path dumps at the Trainer's poll point.
            self._announce("preempt", step, dump=False)
            os.kill(os.getpid(), signal.SIGTERM)
        if self.kill_at_step is not None and step >= self.kill_at_step:
            # SIGKILL gives no grace at all -- dump the ring first;
            # this IS the "what was it doing right before it died"
            # artifact a hard preemption otherwise destroys.
            self._announce("kill", step, dump=True)
            os.kill(os.getpid(), signal.SIGKILL)

    def wants_ckpt_corruption(self, step: int) -> bool:
        return self.active and self.corrupt_ckpt_at_step == step

    def corrupt_checkpoint(self, step_dir: str) -> int:
        """Garbage every regular file under ``step_dir`` (a torn
        multi-file write); returns the count corrupted."""
        n = 0
        for root, _, files in os.walk(step_dir):
            for name in files:
                corrupt_file(os.path.join(root, name))
                n += 1
        return n


def corrupt_file(path: str) -> None:
    """Deterministically destroy a file's contents in place (replace
    with a short garbage header -- breaks zarr/msgpack/json parsing
    alike)."""
    with open(path, "wb") as f:
        f.write(b"\x00TPU_HPC_FAULT_CORRUPTED\x00")


def fault_plan_from_env(env=None) -> Optional[FaultPlan]:
    """Parse ``TPU_HPC_FAULTS`` ("k=v,k=v"); None when unset (the
    production default -- every injection site is a no-op).

    Unknown keys are a hard error: a typo'd fault spec silently
    injecting nothing would make a resilience test pass vacuously.
    """
    env = os.environ if env is None else env
    spec = env.get(ENV_FAULTS, "").strip()
    if not spec:
        return None
    fields: dict = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        key, _, val = part.partition("=")
        key = key.strip()
        if key in _INT_KEYS:
            fields[key] = int(val)
        elif key == "stall_s":
            fields[key] = float(val)
        else:
            raise ValueError(
                f"unknown fault key {key!r} in {ENV_FAULTS}={spec!r} "
                f"(known: {', '.join(_INT_KEYS + ('stall_s',))})"
            )
    return FaultPlan(attempt=current_attempt(env), **fields)
