"""Numeric-health guard: detect poisoned steps, skip or roll back.

The process-level resilience story (signals/supervisor/faults) handles
a run that DIES; nothing before this module defended a run that keeps
stepping while its numerics are garbage. A single non-finite gradient
-- a corrupt batch, an SDC on a chip, fp overflow at a loss spike --
poisons the params within one optimizer update, and every subsequent
step (and checkpoint) is wasted work. The loss-spike/rewind discipline
of large-scale LLM training (the DDP/FSDP characterization study in
PAPERS.md; the SDC sections of "Collective Communication for 100k+
GPUs") is detection-plus-rollback; this module is that discipline as
config:

* the trainer's jitted step computes a tiny fused **health vector**
  per update -- loss finiteness, global grad norm, update norm,
  nonfinite-leaf count (``HEALTH_KEYS``) -- riding the existing
  stacked chunk metrics, so detection costs no extra device round
  trips and no recompiles;
* the host-side :class:`GuardPolicy` classifies every step
  ``healthy`` / ``spike`` / ``poisoned`` against a rolling median of
  recent healthy grad norms, at the chunk boundary where the trainer
  already fetches metrics;
* actions (``TrainingConfig.guard_mode``): ``skip`` drops the
  poisoned update on-device (params/opt-state/model-state keep their
  pre-step values; the step counter -- and with it the data stream --
  still advances), ``rollback`` quarantines any poisoned snapshots,
  records a **skip window** over the poisoned data indices, and exits
  with :data:`~tpu_hpc.resilience.signals.EXIT_ROLLBACK` so the
  supervisor relaunches from the last-good checkpoint -- through the
  ordinary restore path, so rollback works unchanged across an
  elastic pod-shape change (tpu_hpc.reshard handles the move).

Skip windows persist in ``<ckpt_dir>/.tpu_hpc_guard.json``: after the
rollback relaunch the loader fast-forwards past the poisoned batches
(``data_index = step + offset``), so the stream never replays the
batch that poisoned the run. Every decision is a schema-stamped obs
event (``guard_verdict`` / ``guard_rollback``) feeding
``obs.report``'s guard section and the ``regress`` gate's
lower-is-better rollback/skip counters.
"""
from __future__ import annotations

import collections
import dataclasses
import json
import math
import os
import statistics
from typing import Deque, Dict, List, Optional, Sequence

# Metric keys the jitted step emits when the guard is armed (see
# train.trainer.make_step_fn). ``health_skipped`` only exists in skip
# mode; the rest are unconditional with the guard on.
HEALTH_KEYS = (
    "health_loss_finite",
    "health_grad_norm",
    "health_update_norm",
    "health_nonfinite",
    "health_skipped",
)

GUARD_STATE_FILE = ".tpu_hpc_guard.json"
GUARD_STATE_VERSION = 1

GUARD_MODES = ("off", "skip", "rollback")
SPIKE_ACTIONS = ("event", "rollback")


class GuardError(RuntimeError):
    """The guard needed to act but could not (e.g. rollback requested
    with no checkpoint predating the anomaly)."""


@dataclasses.dataclass(frozen=True)
class StepVerdict:
    """One step's classification, host-side."""

    step: int
    verdict: str  # "healthy" | "spike" | "poisoned"
    grad_norm: float
    update_norm: float
    loss_finite: bool
    nonfinite: int
    watermark: Optional[float] = None
    ratio: Optional[float] = None
    skipped: bool = False

    @property
    def healthy(self) -> bool:
        return self.verdict == "healthy"


class GuardPolicy:
    """Rolling-median classifier over per-step health vectors.

    ``spike_factor``: a finite step whose grad norm exceeds
    ``spike_factor x median(recent healthy grad norms)`` is a
    ``spike`` (0 disables spike detection). Only HEALTHY norms enter
    the window -- a diverging run must not re-baseline its own spikes
    into the median. ``min_samples`` healthy steps warm the median up
    before spikes can fire, so step 0's cold norm never false-alarms.
    """

    def __init__(
        self,
        mode: str = "skip",
        spike_factor: float = 10.0,
        spike_action: str = "event",
        window: int = 8,
        min_samples: int = 3,
    ):
        if mode not in GUARD_MODES[1:]:
            raise ValueError(
                f"guard mode {mode!r} must be one of {GUARD_MODES[1:]}"
                " (off = no policy object at all)"
            )
        if spike_factor < 0:
            raise ValueError(
                f"guard_spike_factor {spike_factor} must be >= 0 "
                "(0 = spike detection off)"
            )
        if spike_action not in SPIKE_ACTIONS:
            raise ValueError(
                f"guard_spike_action {spike_action!r} must be one of "
                f"{SPIKE_ACTIONS}"
            )
        if min_samples < 2:
            raise ValueError(
                f"min_samples {min_samples} must be >= 2"
            )
        if window < min_samples:
            raise ValueError(
                f"guard_window {window} must be >= min_samples "
                f"{min_samples}"
            )
        self.mode = mode
        self.spike_factor = spike_factor
        self.spike_action = spike_action
        self.window = window
        self.min_samples = min_samples
        self._norms: Deque[float] = collections.deque(maxlen=window)

    @classmethod
    def from_config(cls, cfg) -> Optional["GuardPolicy"]:
        """Build from a TrainingConfig; None when the guard is off.
        An unknown mode is rejected here, at trainer construction --
        a typo'd guard config must not train unguarded."""
        mode = getattr(cfg, "guard_mode", "off")
        if mode == "off":
            return None
        return cls(
            mode=mode,
            spike_factor=getattr(cfg, "guard_spike_factor", 10.0),
            spike_action=getattr(cfg, "guard_spike_action", "event"),
            window=getattr(cfg, "guard_window", 8),
        )

    @property
    def watermark(self) -> Optional[float]:
        """Median of the recent healthy grad norms; None until warm."""
        if len(self._norms) < self.min_samples:
            return None
        return statistics.median(self._norms)

    def classify(self, step: int, row: Dict[str, float]) -> StepVerdict:
        """Classify one step's health vector. Healthy steps feed the
        rolling median; anomalous ones never do."""
        loss_finite = bool(row.get("health_loss_finite", 1.0) >= 0.5)
        grad_norm = float(row.get("health_grad_norm", 0.0))
        update_norm = float(row.get("health_update_norm", 0.0))
        nonfinite = int(row.get("health_nonfinite", 0))
        skipped = bool(row.get("health_skipped", 0))
        watermark = self.watermark
        if (
            not loss_finite
            or nonfinite > 0
            or not math.isfinite(grad_norm)
            # Finite grads can still overflow the optimizer math
            # (bf16 Adam moments): a non-finite UPDATE is poison too.
            or not math.isfinite(update_norm)
        ):
            return StepVerdict(
                step, "poisoned", grad_norm, update_norm,
                loss_finite, nonfinite, watermark, None, skipped,
            )
        if (
            self.spike_factor > 0
            and watermark is not None
            and watermark > 0
            and grad_norm > self.spike_factor * watermark
        ):
            return StepVerdict(
                step, "spike", grad_norm, update_norm, loss_finite,
                nonfinite, watermark, grad_norm / watermark, skipped,
            )
        self._norms.append(grad_norm)
        return StepVerdict(
            step, "healthy", grad_norm, update_norm, loss_finite,
            nonfinite, watermark, None, skipped,
        )

    def wants_rollback(self, verdict: StepVerdict) -> bool:
        """Does this verdict, under this policy, demand a rollback?"""
        if verdict.verdict == "poisoned":
            return self.mode == "rollback"
        if verdict.verdict == "spike":
            return self.spike_action == "rollback"
        return False


# ---------------------------------------------------------------------
# skip windows: the persisted fast-forward state
# ---------------------------------------------------------------------
def _state_path(ckpt_dir: str) -> str:
    return os.path.join(ckpt_dir, GUARD_STATE_FILE)


def load_state(ckpt_dir: Optional[str]) -> dict:
    """The guard's persisted state for a checkpoint directory:
    ``{"skip_windows": [...], "rollbacks": n}``. Empty-but-valid when
    the file is missing or unreadable (a lost guard file only costs
    the fast-forward -- the run still resumes)."""
    empty = {"skip_windows": [], "rollbacks": 0}
    if not ckpt_dir:
        return empty
    try:
        with open(_state_path(ckpt_dir)) as f:
            data = json.load(f)
    except (OSError, ValueError):
        return empty
    if not isinstance(data, dict):
        return empty
    data.setdefault("skip_windows", [])
    data.setdefault("rollbacks", 0)
    return data


def record_rollback(ckpt_dir: str, window: dict) -> dict:
    """Append one skip window atomically and bump the rollback count;
    returns the new state. ``window`` carries ``from_step`` (the first
    anomalous optimizer step) and ``data_from``/``data_to`` (the
    poisoned data-index span the stream must never replay)."""
    state = load_state(ckpt_dir)
    state["skip_windows"] = sorted(
        [*state["skip_windows"], dict(window)],
        key=lambda w: int(w["from_step"]),
    )
    state["rollbacks"] = int(state.get("rollbacks", 0)) + 1
    state["schema_version"] = GUARD_STATE_VERSION
    path = _state_path(ckpt_dir)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(state, f)
    os.replace(tmp, path)
    return state


def window_width(window: dict) -> int:
    return int(window["data_to"]) - int(window["data_from"]) + 1


def offset_at(windows: Sequence[dict], step: int) -> int:
    """Cumulative data-stream offset at optimizer step ``step``:
    ``data_index = step + offset_at(...)``. Each window shifts every
    step at or past its ``from_step`` by the window's width, so the
    poisoned span of data indices is never consumed again while the
    pre-anomaly steps replay their original batches exactly."""
    return sum(
        window_width(w) for w in windows
        if step >= int(w["from_step"])
    )


def next_boundary(
    windows: Sequence[dict], step: int
) -> Optional[int]:
    """The next step at which the offset changes (the trainer caps
    its chunk there so one chunk never spans two offsets), or None."""
    future = [
        int(w["from_step"]) for w in windows
        if int(w["from_step"]) > step
    ]
    return min(future) if future else None


def health_rows(
    stacked: Dict[str, "object"], chunk: int
) -> List[Dict[str, float]]:
    """Split fetched per-chunk health arrays (numpy, shape [chunk])
    into one dict per step, in chunk order."""
    rows: List[Dict[str, float]] = []
    for i in range(chunk):
        rows.append({
            k: float(v[i]) for k, v in stacked.items()
        })
    return rows
