"""Unified training configuration: dataclass + YAML + CLI.

Parity with the reference's ``utils/config.py`` (TrainingConfig
dataclass :25-51, ``from_yaml`` :56-71, ``from_args`` :73-122), which
was defined but never adopted by the example scripts (SURVEY.md 2.7).
Here it IS the single config layer: every example and the Trainer take
one of these. ``parse_known_args`` tolerance is kept so recipes can add
their own flags.

TPU-specific deltas from the reference fields:
  * ``backend`` (nccl/gloo/mpi) is gone -- XLA owns the transport.
  * ``use_amp``/``amp_dtype`` become ``param_dtype``/``compute_dtype``:
    on TPU bf16 compute is the default, not an option bolted on.
  * mesh axis sizes (data/model/seq/pipe) are config, promoting the
    reference's hard-coded ``tp_size = 4`` constants
    (scripts/06_hybrid_parallelism/01_fsdp_tp_hybrid.py:73) to flags.
"""
from __future__ import annotations

import argparse
import dataclasses
from typing import Any, Optional, Sequence


@dataclasses.dataclass
class TrainingConfig:
    # Optimization (reference: utils/config.py:27-35).
    epochs: int = 5
    global_batch_size: int = 32
    learning_rate: float = 1e-3
    weight_decay: float = 0.0
    momentum: float = 0.9
    seed: int = 42
    steps_per_epoch: int = 50
    # Gradient accumulation: microbatches per optimizer update. The
    # global batch is split into this many sequential forward/backward
    # passes inside the jitted step -- same optimizer trajectory at
    # 1/N the activation memory (how large global batches fit HBM at
    # 7B scale). 1 = off.
    grad_accum_steps: int = 1
    # LR schedule: "constant" (the reference's fixed-lr examples) or
    # "cosine" (warmup -> cosine decay over the whole run, the standard
    # LLM pretraining shape). warmup_steps applies to both.
    lr_schedule: str = "constant"
    warmup_steps: int = 0
    # Global gradient-norm clip before the optimizer update (standard
    # LLM pretraining stabilizer, typically 1.0; the reference never
    # needed it for its toy steps). Applied to the full accumulated
    # gradient, so the clip threshold is accum-invariant. 0 = off.
    max_grad_norm: float = 0.0
    # AdamW moment dtype: "float32" (default; exact parity with the
    # reference's AdamW) or "bfloat16" -- halves optimizer-state HBM
    # (the documented unlock for 70B-class models on 16 GiB chips,
    # REPORT_70b_128chip_2M.md) at a small update-noise cost. Applies
    # to both mu and nu; master params stay fp32 either way.
    adam_moments_dtype: str = "float32"

    # Precision (reference AMP block: utils/config.py:40-44).
    param_dtype: str = "float32"
    compute_dtype: str = "bfloat16"

    # Mesh (replaces hard-coded tp_size constants; SURVEY.md 5.6).
    data_parallel: int = -1  # -1 = all remaining devices
    model_parallel: int = 1
    seq_parallel: int = 1
    pipe_parallel: int = 1
    # Multi-slice: how many TPU slices the data axis spans over DCN
    # (1 = single slice). The reference's FSDP-across-nodes-on-Slingshot
    # doctrine (fsdp_tp/fsdp_tp_example.py:12-26); data_parallel then
    # gives the PER-SLICE extent (or -1 for all remaining per-slice
    # chips).
    dcn_data_parallel: int = 1

    # Checkpointing (reference: utils/config.py:45-47).
    save_every: int = 0  # epochs; 0 = off
    checkpoint_dir: str = "checkpoints"
    resume: bool = True
    # Elastic resume (tpu_hpc.reshard): peak per-device transient, in
    # MiB, a cross-topology restore's reshard plan may materialize
    # (restore_latest max_inflight_bytes). 0 = unbounded -- fine on
    # meshes with HBM headroom; set it on runs whose state is a large
    # fraction of the chip, where an unbounded cross-mesh move is free
    # to stage a full-array transient per device.
    reshard_max_inflight_mb: int = 0

    # Numeric-health guard (tpu_hpc.resilience.guard): the jitted step
    # emits a fused health vector (loss finiteness, global grad/update
    # norms, nonfinite-leaf count) and a host-side policy classifies
    # every step healthy/spike/poisoned at the chunk boundaries the
    # trainer already owns -- no extra device round trips, no
    # recompiles. Actions on a poisoned (non-finite) step:
    #   "off"      -- no health vector, byte-identical step program to
    #                 a pre-guard trainer (the default).
    #   "skip"     -- drop the update on-device (params/opt/model
    #                 state keep their pre-step values), advance the
    #                 data stream, keep going.
    #   "rollback" -- quarantine poisoned snapshots, persist a skip
    #                 window over the poisoned data indices, and exit
    #                 EXIT_ROLLBACK(77); the supervisor relaunches
    #                 from the last-good checkpoint (its own
    #                 --max-rollbacks budget) and the stream
    #                 fast-forwards past the poisoned batches.
    #                 Requires a checkpoint manager.
    guard_mode: str = "off"
    # A finite step whose global grad norm exceeds guard_spike_factor
    # x the rolling median of recent healthy norms is a "spike"
    # (0 = spike detection off). guard_spike_action: "event" records
    # the schema-stamped guard_verdict and keeps going; "rollback"
    # treats the spike like a poisoned step (the loss-spike/rewind
    # discipline of large-scale LLM training).
    guard_spike_factor: float = 10.0
    guard_spike_action: str = "event"
    # Rolling-median window (healthy steps) for spike detection.
    guard_window: int = 8

    # Profiling (reference: utils/config.py:48-50).
    profile: bool = False
    profile_dir: str = "profiles"
    profile_start_step: int = 3
    profile_num_steps: int = 5
    # Anomaly-triggered capture (obs/trace.py): when the stall
    # watermark trips or the numeric-health guard classifies a
    # poisoned step, auto-arm ONE bounded jax.profiler trace covering
    # the next capture_steps steps plus a correlated flight-ring dump
    # and device-memory snapshot, all keyed by the triggering step's
    # trace id. Evidence lands under <checkpoint_dir or profile_dir>/
    # anomaly. Off by default: it shares the single jax.profiler
    # slot with `profile`.
    capture_on_anomaly: bool = False
    capture_steps: int = 2

    # Gradient-sync strategy (the comm-performance layer,
    # tpu_hpc.comm): "flat" = GSPMD's fused collectives (the default,
    # byte-identical to the pre-comm_mode trainer); "bucketed_overlap"
    # = explicit per-shard grads inside shard_map, reduced in
    # size-capped buckets (DDP bucketing TPU-natively -- separate
    # collectives the latency-hiding scheduler overlaps with backward
    # compute); "hierarchical" = bucketed + each bucket reduced as ICI
    # reduce-scatter -> DCN all-reduce -> ICI all-gather, so only the
    # 1/n_ici shard crosses DCN (needs a two-axis data mesh, batch
    # sharded P((dcn, data)) with the DCN axis outer). Manual modes
    # require replicated params (DDP-style); FSDP/TP-sharded plans
    # keep "flat" (fsdp.validate_grad_sync_mode enforces this).
    # "auto" = ask the topology-aware collective planner
    # (tpu_hpc.comm.planner): the mode AND bucket size come from the
    # mesh's measured cost table (an alpha-beta latency/bandwidth
    # model when no table exists), sharded plans resolve to flat, and
    # the decision is logged as a schema-stamped comm_plan event.
    comm_mode: str = "flat"
    # Bucket size cap for the manual comm modes, in MiB (DDP's 25 MiB
    # default: big enough to amortize collective launch latency, small
    # enough that buckets pipeline within one backward). Under
    # comm_mode="auto" this caps the planner's bucket ladder.
    comm_bucket_mb: int = 25

    # Run metrics log: when set, host 0 appends one JSON line per
    # epoch chunk (loss, throughput, step time) plus a run-start
    # record with env metadata -- the reference's append-only
    # benchmark_results.log / metadata-rich CSV discipline
    # (scripts/main.py:381-397, tests/torch_comm_bench.py:137-194)
    # as structured JSONL. "" = off.
    metrics_path: str = ""
    # Model cost for post-hoc MFU: FLOPs one training step spends per
    # item (per token for LLMs -- the 6N estimate -- per sample
    # otherwise). Stamped into the run_start record's config, it lets
    # ``python -m tpu_hpc.obs.report`` compute run MFU from the JSONL
    # alone, on a machine with no TPU attached. 0 = unknown (the
    # report says so instead of guessing).
    model_flops_per_item: float = 0.0

    @classmethod
    def from_yaml(cls, path: str) -> "TrainingConfig":
        """Load from a YAML mapping; unknown keys rejected.
        Parity: utils/config.py:56-71."""
        import yaml

        with open(path) as f:
            raw = yaml.safe_load(f) or {}
        fields = {f.name for f in dataclasses.fields(cls)}
        unknown = set(raw) - fields
        if unknown:
            raise ValueError(f"unknown config keys in {path}: {sorted(unknown)}")
        return cls(**raw)

    def to_yaml(self, path: str) -> str:
        """Write the effective config as YAML (round-trips through
        ``from_yaml``). The Trainer snapshots this into the checkpoint
        directory so a resumed or audited run knows exactly what
        hyperparameters produced it -- the recorded-environment
        discipline of the reference's benchmark CSV headers
        (tests/torch_comm_bench.py:153-194) applied to training runs.
        """
        import os

        import yaml

        # Atomic: a crash mid-write must not leave a truncated YAML
        # that from_yaml would silently fill with defaults.
        tmp = f"{path}.tmp"
        with open(tmp, "w") as f:
            yaml.safe_dump(
                dataclasses.asdict(self), f, sort_keys=False
            )
        os.replace(tmp, path)
        return path

    @classmethod
    def from_args(
        cls, argv: Optional[Sequence[str]] = None
    ) -> "TrainingConfig":
        """Build from CLI flags; tolerates extra flags via
        parse_known_args. Parity: utils/config.py:73-122."""
        p = argparse.ArgumentParser(add_help=False)
        p.add_argument("--config", type=str, default=None, help="YAML config path")
        for f in dataclasses.fields(cls):
            flag = "--" + f.name.replace("_", "-")
            if f.type == "bool" or isinstance(f.default, bool):
                p.add_argument(
                    flag,
                    type=lambda s: s.lower() in ("1", "true", "yes"),
                    default=None,
                )
            else:
                p.add_argument(flag, type=type(f.default), default=None)
        ns, _ = p.parse_known_args(argv)
        base = cls.from_yaml(ns.config) if ns.config else cls()
        for f in dataclasses.fields(cls):
            v = getattr(ns, f.name, None)
            if v is not None:
                setattr(base, f.name, v)
        return base

    def jax_dtypes(self) -> "tuple[Any, Any]":
        """(param_dtype, compute_dtype) as jax dtypes -- the plumbing
        for the reference's --use-amp/amp_dtype switch
        (resnet_fsdp_training.py:198-204): pass into a model config as
        ``SomeConfig(dtype=compute, param_dtype=param)``. fp32 params +
        bf16 compute is the TPU-native mixed-precision default."""
        import jax.numpy as jnp

        allowed = {"float32", "bfloat16", "float16"}
        for name in (self.param_dtype, self.compute_dtype):
            if name not in allowed:
                raise ValueError(
                    f"unsupported dtype {name!r}; expected one of "
                    f"{sorted(allowed)}"
                )
        return jnp.dtype(self.param_dtype), jnp.dtype(self.compute_dtype)

    def mesh_axes(self) -> "dict[str, int]":
        """Ordered mesh axes, dropping degenerate (size-1) ones except
        data. Data first = bandwidth-tolerant axis on the outer ring."""
        axes: dict[str, int] = {}
        if self.pipe_parallel > 1:
            axes["pipe"] = self.pipe_parallel
        axes["data"] = self.data_parallel
        if self.seq_parallel > 1:
            axes["seq"] = self.seq_parallel
        if self.model_parallel > 1:
            axes["model"] = self.model_parallel
        return axes

    def mesh_spec(self) -> Any:
        """Full ``MeshSpec`` including the multi-slice (DCN) extent of
        the data axis. Use ``build_mesh(cfg.mesh_spec())`` in recipes
        that may run across slices."""
        from tpu_hpc.runtime.mesh import MeshSpec

        if self.dcn_data_parallel < 1:
            raise ValueError(
                f"dcn_data_parallel must be >= 1, got "
                f"{self.dcn_data_parallel}"
            )
        dcn = (
            {"data": self.dcn_data_parallel}
            if self.dcn_data_parallel > 1
            else {}
        )
        return MeshSpec(axes=self.mesh_axes(), dcn_axes=dcn)
