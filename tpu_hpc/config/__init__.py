from tpu_hpc.config.config import TrainingConfig  # noqa: F401
