"""Fleet rollup aggregator + live scoreboard over digest channels.

The read side of the live plane: tail every per-process digest channel
under ``$TPU_HPC_DIGEST_DIR`` (obs/digest.py), merge into one fleet
view keyed by (role, key) -- occupancy, KV pressure, SLO attainment,
bubble fraction, step time -- and judge two fleet-level verdicts the
per-process view structurally cannot:

* **straggler**: a key whose normalized step signal (the StallDetector
  watermark the supervisor already trusts, falling back to the last
  step time) exceeds ``straggler_factor`` x the median of its *peers*
  -- self-excluded, the PR-14/15 idiom: N-1 healthy members pin the
  baseline, so one slow member cannot drag the median toward itself;
* **stale**: a publisher whose newest digest is older than
  ``stale_after_s``. Absence of telemetry is a first-class signal
  (``digest_stale``), not a silently thinner rollup -- the wedged
  process is precisely the one that stops publishing.

The merge is idempotent and order-free: sources are keyed by
(role, key, host, pid) and only the highest-``seq`` record per source
is kept, so re-reading a channel, reading channels in any order, or
merging partial rollups from two aggregators all converge to the same
view (property-tested in tests/test_live.py). Counters are cumulative
in the digests, so cross-source aggregation is plain summation.

``python -m tpu_hpc.obs.live DIR --json`` is the driver contract (one
deterministic JSON document, floats rounded, no wall-clock or
host/pid fields); ``--watch`` renders a refreshing terminal
scoreboard; ``--prom`` writes the fleet-merged Prometheus textfile
(one atomic file for the whole fleet -- per-process files unchanged).
"""
from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import time
from typing import Dict, List, Mapping, Optional, Tuple

from tpu_hpc.obs.digest import (
    ENV_DIGEST_DIR,
    LogBucketSketch,
    read_digest_dir,
)
from tpu_hpc.obs.schema import SCHEMA_VERSION

ENV_FLEET_PROM_FILE = "TPU_HPC_FLEET_PROM_FILE"

DEFAULT_STALE_AFTER_S = 15.0
DEFAULT_STRAGGLER_FACTOR = 3.0

# One publishing process: the dedup unit. A restarted process (new
# pid) is a NEW source under the same (role, key) -- its counters sum
# with its predecessor's final cumulative totals instead of silently
# replacing them.
_SourceKey = Tuple[str, str, str, int]


def _r(x: float, nd: int = 6) -> float:
    """Rollup floats are rounded so the --json document is stable
    across platforms' float formatting."""
    return round(float(x), nd)


class Rollup:
    """Mergeable fleet view over ``health_digest`` records."""

    def __init__(
        self,
        stale_after_s: float = DEFAULT_STALE_AFTER_S,
        straggler_factor: float = DEFAULT_STRAGGLER_FACTOR,
    ):
        if stale_after_s <= 0:
            raise ValueError(
                f"stale_after_s {stale_after_s} must be > 0"
            )
        if straggler_factor <= 1.0:
            raise ValueError(
                f"straggler_factor {straggler_factor} must be > 1"
            )
        self.stale_after_s = stale_after_s
        self.straggler_factor = straggler_factor
        self._sources: Dict[_SourceKey, dict] = {}
        self.digests = 0

    # -- write side ----------------------------------------------------
    def ingest(self, records) -> "Rollup":
        """Fold digest records in; keeps the latest ``seq`` per source
        (ties broken by ``t``). Duplicate or out-of-order delivery is
        a no-op -- the idempotence the merge algebra rests on."""
        for rec in records:
            if rec.get("event") != "health_digest":
                continue
            self.digests += 1
            src: _SourceKey = (
                str(rec.get("role")), str(rec.get("key")),
                str(rec.get("host", "")), int(rec.get("pid", 0)),
            )
            cur = self._sources.get(src)
            if cur is not None:
                key_new = (int(rec.get("seq", 0)), float(rec.get("t", 0.0)))
                key_cur = (int(cur.get("seq", 0)), float(cur.get("t", 0.0)))
                if key_new <= key_cur:
                    continue
            self._sources[src] = rec
        return self

    def merge(self, other: "Rollup") -> "Rollup":
        """In-place merge of another rollup (two aggregators covering
        overlapping channel sets converge): per-source latest wins."""
        for src, rec in other._sources.items():
            cur = self._sources.get(src)
            if cur is not None:
                key_new = (int(rec.get("seq", 0)), float(rec.get("t", 0.0)))
                key_cur = (int(cur.get("seq", 0)), float(cur.get("t", 0.0)))
                if key_new <= key_cur:
                    continue
            self._sources[src] = rec
        self.digests += other.digests
        return self

    # -- read side -----------------------------------------------------
    def latest_t(self) -> Optional[float]:
        if not self._sources:
            return None
        return max(float(r.get("t", 0.0)) for r in self._sources.values())

    def build(self, now: Optional[float] = None) -> dict:
        """The fleet view as one deterministic JSON-safe document.
        ``now`` defaults to the newest digest time seen -- the only
        deterministic notion of "now" an offline/virtual-clock reader
        has; live watchers pass wall time."""
        if now is None:
            now = self.latest_t() or 0.0
        # (role, key) -> list of that pair's latest-per-source records.
        by_rk: Dict[Tuple[str, str], List[dict]] = {}
        for (role, key, _h, _p), rec in sorted(self._sources.items()):
            by_rk.setdefault((role, key), []).append(rec)

        roles: Dict[str, dict] = {}
        for (role, key), recs in sorted(by_rk.items()):
            latest = max(
                recs,
                key=lambda r: (float(r.get("t", 0.0)), int(r.get("seq", 0))),
            )
            counters: Dict[str, float] = {}
            hists: Dict[str, LogBucketSketch] = {}
            for rec in recs:
                for name, v in (rec.get("counters") or {}).items():
                    counters[name] = counters.get(name, 0.0) + float(v)
                for name, d in (rec.get("hists") or {}).items():
                    sk = LogBucketSketch.from_dict(d)
                    if name in hists:
                        hists[name].merge(sk)
                    else:
                        hists[name] = sk
            age = now - float(latest.get("t", 0.0))
            row: dict = {
                "seq": int(latest.get("seq", 0)),
                "t": _r(float(latest.get("t", 0.0))),
                "age_s": _r(age),
                "sources": len(recs),
                "counters": {
                    k: _r(v) for k, v in sorted(counters.items())
                },
                "gauges": {
                    k: _r(float(v))
                    for k, v in sorted((latest.get("gauges") or {}).items())
                },
                "hists": {
                    k: {f: _r(v) for f, v in hists[k].summary().items()}
                    for k in sorted(hists)
                },
                "stale": bool(age > self.stale_after_s),
                "straggler": False,  # judged below, needs peers
            }
            for f in ("step_s", "watermark_s"):
                if latest.get(f) is not None:
                    row[f] = _r(float(latest[f]))
            roles.setdefault(role, {"keys": {}})["keys"][key] = row
            row["_sketches"] = hists  # stripped before return

        # Straggler verdicts: within each role, compare every key's
        # normalized step signal to the median of its PEERS (self
        # excluded). >= 2 peers required -- with one peer the "median"
        # is just the other member and either could be the slow one.
        for role, block in roles.items():
            keys = block["keys"]
            signals = {
                k: (row.get("watermark_s") or row.get("step_s"))
                for k, row in keys.items()
            }
            for k, row in keys.items():
                v = signals.get(k)
                if v is None:
                    continue
                peers = [
                    s for pk, s in signals.items()
                    if pk != k and s is not None
                ]
                if len(peers) < 2:
                    continue
                med = statistics.median(peers)
                if med > 0 and v > self.straggler_factor * med:
                    row["straggler"] = True

        # Role-level aggregates + verdict lists.
        stragglers: List[str] = []
        stale: List[str] = []
        for role, block in sorted(roles.items()):
            keys = block["keys"]
            counters: Dict[str, float] = {}
            hists: Dict[str, LogBucketSketch] = {}
            for key, row in sorted(keys.items()):
                for name, v in row["counters"].items():
                    counters[name] = counters.get(name, 0.0) + v
                for name, sk in row.pop("_sketches").items():
                    if name in hists:
                        hists[name].merge(sk)
                    else:
                        hists[name] = sk
                if row["straggler"]:
                    stragglers.append(f"{role}:{key}")
                if row["stale"]:
                    stale.append(f"{role}:{key}")
            block["counters"] = {
                k: _r(v) for k, v in sorted(counters.items())
            }
            block["hists"] = {
                k: {f: _r(v) for f, v in hists[k].summary().items()}
                for k in sorted(hists)
            }
            block["stragglers"] = sorted(
                k for k, row in keys.items() if row["straggler"]
            )
            block["stale"] = sorted(
                k for k, row in keys.items() if row["stale"]
            )

        out: dict = {
            "schema_version": SCHEMA_VERSION,
            "now": _r(now),
            "sources": len(self._sources),
            "digests": self.digests,
            "stale_after_s": self.stale_after_s,
            "straggler_factor": self.straggler_factor,
            "roles": roles,
            "stragglers": sorted(stragglers),
            "stale": sorted(stale),
        }
        # Fleet SLO attainment from the cumulative slo_good/slo_bad
        # counters any producer may carry (serve/fleet.py does).
        good = bad = 0.0
        for block in roles.values():
            good += block["counters"].get("slo_good", 0.0)
            bad += block["counters"].get("slo_bad", 0.0)
        if good + bad > 0:
            out["slo"] = {
                "good": _r(good),
                "bad": _r(bad),
                "attainment": _r(good / (good + bad)),
            }
        else:
            out["slo"] = None
        return out


def rollup_from_dir(
    dir: str,
    *,
    stale_after_s: float = DEFAULT_STALE_AFTER_S,
    straggler_factor: float = DEFAULT_STRAGGLER_FACTOR,
) -> Rollup:
    """One-shot: read every channel under ``dir`` into a Rollup."""
    roll = Rollup(
        stale_after_s=stale_after_s, straggler_factor=straggler_factor
    )
    roll.ingest(read_digest_dir(dir))
    return roll


def stale_entries(view: Mapping) -> List[dict]:
    """The ``digest_stale`` payloads a built view implies -- what a
    producer-side monitor (FleetTelemetry, the supervisor) emits, one
    record per stale (role, key)."""
    out: List[dict] = []
    for role, block in (view.get("roles") or {}).items():
        for key, row in block["keys"].items():
            if row.get("stale"):
                out.append({
                    "role": role,
                    "key": key,
                    "age_s": row["age_s"],
                    "stale_after_s": view.get("stale_after_s"),
                    "last_t": row["t"],
                    "last_seq": row["seq"],
                })
    return sorted(out, key=lambda d: (d["role"], d["key"]))


# -- scoreboard ---------------------------------------------------------
def format_scoreboard(view: Mapping) -> str:
    """Terminal rendering of a built view: one row per (role, key),
    verdict flags inline, fleet SLO at the foot."""
    lines = [
        f"fleet rollup @ t={view['now']}  sources={view['sources']}  "
        f"digests={view['digests']}  stragglers={len(view['stragglers'])}"
        f"  stale={len(view['stale'])}"
    ]
    header = (
        f"{'role':<10} {'key':<8} {'age_s':>8} {'step_s':>9} "
        f"{'watermark':>10}  gauges / flags"
    )
    lines += [header, "-" * len(header)]
    for role, block in sorted((view.get("roles") or {}).items()):
        for key, row in sorted(block["keys"].items()):
            gauges = " ".join(
                f"{k}={v}" for k, v in list(row["gauges"].items())[:3]
            )
            flags = []
            if row.get("straggler"):
                flags.append("STRAGGLER")
            if row.get("stale"):
                flags.append("STALE")
            step_s = row.get("step_s")
            wm = row.get("watermark_s")
            lines.append(
                f"{role:<10} {key:<8} {row['age_s']:>8.3f} "
                f"{(f'{step_s:.4f}' if step_s is not None else '-'):>9} "
                f"{(f'{wm:.4f}' if wm is not None else '-'):>10}  "
                f"{gauges}{('  ' + ' '.join(flags)) if flags else ''}"
            )
    slo = view.get("slo")
    if slo:
        lines.append(
            f"SLO: attainment {slo['attainment']:.4f} "
            f"(good {slo['good']:g} / bad {slo['bad']:g})"
        )
    return "\n".join(lines)


# -- fleet-merged Prometheus textfile -----------------------------------
def fleet_prometheus_text(
    view: Mapping, prefix: str = "tpu_hpc_fleet"
) -> str:
    """The whole fleet in one exposition: per-key counters/gauges with
    ``role``/``key`` labels, merged per-role histogram quantiles
    (p50/p95/p99/p99.9 from the mergeable sketches), and the verdict
    gauges. Per-process textfiles (registry.write_prometheus) are
    untouched -- this is the aggregator's file."""
    from tpu_hpc.obs.registry import _sanitize

    lines: List[str] = []
    for role, block in sorted((view.get("roles") or {}).items()):
        for key, row in sorted(block["keys"].items()):
            lab = f'role="{role}",key="{key}"'
            for name, v in sorted(row["counters"].items()):
                lines.append(
                    f"{prefix}_{_sanitize(name)}{{{lab}}} {v}"
                )
            for name, v in sorted(row["gauges"].items()):
                lines.append(
                    f"{prefix}_{_sanitize(name)}{{{lab}}} {v}"
                )
            lines.append(
                f"{prefix}_digest_age_s{{{lab}}} {row['age_s']}"
            )
            lines.append(
                f"{prefix}_straggler{{{lab}}} "
                f"{1 if row.get('straggler') else 0}"
            )
            lines.append(
                f"{prefix}_digest_stale{{{lab}}} "
                f"{1 if row.get('stale') else 0}"
            )
        for name, s in sorted(block["hists"].items()):
            m = f"{prefix}_{_sanitize(name)}"
            for q, f in (("0.5", "p50"), ("0.95", "p95"),
                         ("0.99", "p99"), ("0.999", "p999")):
                lines.append(
                    f'{m}{{role="{role}",quantile="{q}"}} {s[f]}'
                )
            lines.append(f'{m}_sum{{role="{role}"}} {s["sum"]}')
            lines.append(f'{m}_count{{role="{role}"}} {s["count"]}')
    slo = view.get("slo")
    if slo:
        lines.append(f"{prefix}_slo_attainment {slo['attainment']}")
    return "\n".join(lines) + ("\n" if lines else "")


def write_fleet_prometheus(
    view: Mapping,
    path: Optional[str] = None,
    prefix: str = "tpu_hpc_fleet",
) -> Optional[str]:
    """Atomic tmp+rename (the textfile-collector contract, same as
    registry.write_prometheus). ``path`` defaults to
    ``$TPU_HPC_FLEET_PROM_FILE``; with neither, a no-op."""
    path = path or os.environ.get(ENV_FLEET_PROM_FILE)
    if not path:
        return None
    parent = os.path.dirname(path)
    if parent:
        os.makedirs(parent, exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(fleet_prometheus_text(view, prefix))
    os.replace(tmp, path)
    return path


# -- digest-plane micro-bench (the banked overhead evidence) ------------
def bench_live(out_path: str, n_publish: int = 64) -> List[dict]:
    """Measure the plane's own cost and error bound; appends two
    ``bench`` records to ``out_path`` and returns them:

    * ``obs.digest_publish_ms`` -- median wall cost of one
      ``DigestPublisher.publish`` (build + stamp + append) with a
      registry-shaped payload;
    * ``obs.digest_quantile_rel_err`` -- worst observed relative error
      of merged-sketch quantiles vs exact nearest-rank over a
      deterministic two-stream workload (must sit under the pinned
      DEFAULT_ALPHA bound).

    This is how BENCH_LIVE rows are (re)generated:
    ``python -m tpu_hpc.obs.live --bench BENCH_LIVE_rN.jsonl``.
    """
    import random
    import tempfile

    from tpu_hpc.obs.digest import DEFAULT_ALPHA, DigestPublisher
    from tpu_hpc.obs.events import get_bus

    rng = random.Random(20260807)
    # -- publish cost --
    durs: List[float] = []
    with tempfile.TemporaryDirectory() as td:
        pub = DigestPublisher(td, "bench", "0")
        sketch = LogBucketSketch()
        for _ in range(2048):
            sketch.add(rng.lognormvariate(1.0, 1.0))
        counters = {f"c{i}": float(i * 7) for i in range(24)}
        gauges = {f"g{i}": i / 3.0 for i in range(12)}
        hists = {f"h{i}": sketch for i in range(4)}
        for i in range(n_publish):
            t0 = time.perf_counter()
            pub.publish(
                counters=counters, gauges=gauges, hists=hists,
                t=float(i),
            )
            durs.append((time.perf_counter() - t0) * 1e3)
    durs.sort()
    publish_ms = durs[len(durs) // 2]

    # -- merged-quantile error vs exact nearest-rank --
    streams = [
        [rng.lognormvariate(0.0, 2.0) for _ in range(4000)],
        [rng.uniform(0.5, 50.0) for _ in range(4000)],
    ]
    sketches = []
    for s in streams:
        sk = LogBucketSketch()
        for v in s:
            sk.add(v)
        sketches.append(sk)
    merged = sketches[0].merge(sketches[1])
    union = sorted(streams[0] + streams[1])
    worst = 0.0
    import math
    for q in (0.5, 0.9, 0.95, 0.99, 0.999):
        exact = union[max(0, math.ceil(q * len(union)) - 1)]
        est = merged.quantile(q)
        worst = max(worst, abs(est - exact) / exact)

    bus = get_bus()
    rows = [
        bus.emit(
            "bench", sink=out_path, metric="obs.digest_publish_ms",
            value=round(publish_ms, 4), unit="ms",
            n_publish=n_publish, n_counters=len(counters),
            n_gauges=len(gauges), n_hists=len(hists),
            workload="digest_publish",
        ),
        bus.emit(
            "bench", sink=out_path,
            metric="obs.digest_quantile_rel_err",
            value=round(worst, 6), unit="ratio",
            alpha=DEFAULT_ALPHA, n_values=len(union),
            workload="digest_merge_quantiles",
        ),
    ]
    return rows


# -- CLI ----------------------------------------------------------------
def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m tpu_hpc.obs.live",
        description="Fleet rollup over health-digest channels.",
    )
    p.add_argument(
        "dir", nargs="?", default=os.environ.get(ENV_DIGEST_DIR),
        help=f"digest channel directory (default ${ENV_DIGEST_DIR})",
    )
    p.add_argument("--json", action="store_true",
                   help="print the rollup as one JSON document")
    p.add_argument("--watch", action="store_true",
                   help="refreshing terminal scoreboard")
    p.add_argument("--interval", type=float, default=2.0,
                   help="--watch refresh period (s)")
    p.add_argument("--now", type=float, default=None,
                   help="override 'now' (virtual-clock runs); default "
                        "is the newest digest time seen")
    p.add_argument("--stale-after", type=float,
                   default=DEFAULT_STALE_AFTER_S,
                   help="seconds without a digest before a publisher "
                        "is flagged stale")
    p.add_argument("--straggler-factor", type=float,
                   default=DEFAULT_STRAGGLER_FACTOR,
                   help="x peer-median threshold for the straggler "
                        "verdict")
    p.add_argument("--prom", default=None, metavar="FILE",
                   help="also write the fleet-merged Prometheus "
                        "textfile here")
    p.add_argument("--bench", default=None, metavar="FILE",
                   help="measure digest publish cost + sketch error "
                        "bound; append bench rows to FILE and exit")
    args = p.parse_args(argv)

    if args.bench:
        rows = bench_live(args.bench)
        for r in rows:
            print(f"{r['metric']} = {r['value']} {r['unit']}")
        return 0

    if not args.dir:
        print(
            f"error: no digest dir (pass DIR or set ${ENV_DIGEST_DIR})",
            file=sys.stderr,
        )
        return 2

    def snapshot(now: Optional[float]) -> dict:
        roll = rollup_from_dir(
            args.dir,
            stale_after_s=args.stale_after,
            straggler_factor=args.straggler_factor,
        )
        view = roll.build(now=now)
        if args.prom:
            write_fleet_prometheus(view, args.prom)
        return view

    if args.watch:
        try:
            while True:
                view = snapshot(args.now or time.time())
                sys.stdout.write(
                    "\x1b[2J\x1b[H" + format_scoreboard(view) + "\n"
                )
                sys.stdout.flush()
                time.sleep(args.interval)
        except KeyboardInterrupt:
            return 0

    view = snapshot(args.now)
    if view["sources"] == 0:
        print(
            f"error: no health digests under {args.dir}",
            file=sys.stderr,
        )
        return 2
    if args.json:
        print(json.dumps(view, indent=2, sort_keys=True))
    else:
        print(format_scoreboard(view))
    return 0


if __name__ == "__main__":  # pragma: no cover - exercised via CLI tests
    raise SystemExit(main())
