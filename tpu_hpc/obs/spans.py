"""Nestable span timers: one timing truth for JSONL and XProf.

``with span("ckpt"): ...`` measures a wall-clock duration, emits a
``span`` event through the bus, and (by default) opens a
``jax.profiler.TraceAnnotation`` of the same name -- so the phase
boundaries in a run's JSONL and the named regions in an XProf trace
are the SAME brackets, not two instrumentation layers that drift.
Spans nest: each event carries its ``parent`` span name and depth, so
the report can attribute child time without double counting.

Clock contract (pinned in tests/test_trace.py): **durations come from
the monotonic clock** (``time.perf_counter``), never wall time -- an
NTP step mid-span must not corrupt a phase share. Every span event
also carries ``t_mono`` (the monotonic timestamp at span end, same
clock as the duration) next to the bus-stamped wall ``time``: a
cross-host trace merge (obs/trace.py) orders and measures each host
on its own monotonic axis and uses wall time only for coarse
alignment between hosts.

For phases whose duration is measured some other way (the Trainer's
chunk timer already brackets dispatch-to-fetch), :func:`emit_span`
records a pre-aggregated duration without re-timing it.
"""
from __future__ import annotations

import contextlib
import threading
import time
from typing import Iterator, Optional

from tpu_hpc.obs.events import EventBus, get_bus

_stack = threading.local()


def _current_stack() -> list:
    st = getattr(_stack, "names", None)
    if st is None:
        st = _stack.names = []
    return st


def emit_span(
    name: str,
    dur_s: float,
    *,
    bus: Optional[EventBus] = None,
    sink: Optional[str] = None,
    step: Optional[int] = None,
    hist: Optional[str] = None,
    **fields,
) -> dict:
    """Emit one ``span`` record for an already-measured duration.
    ``hist`` additionally observes the duration into the global
    metrics registry under that histogram name."""
    if hist is not None:
        from tpu_hpc.obs.registry import get_registry

        get_registry().observe(hist, dur_s)
    st = _current_stack()
    return (bus or get_bus()).emit(
        "span",
        sink=sink,
        name=name,
        dur_s=dur_s,
        t_mono=time.perf_counter(),
        step=step,
        parent=st[-1] if st else None,
        depth=len(st),
        **fields,
    )


@contextlib.contextmanager
def span(
    name: str,
    *,
    bus: Optional[EventBus] = None,
    sink: Optional[str] = None,
    step: Optional[int] = None,
    annotate: bool = True,
    hist: Optional[str] = None,
    **fields,
) -> Iterator[None]:
    """Time a block as a named span.

    Emits the ``span`` event in a ``finally`` (an exception inside the
    block still records the phase and its duration -- the flight
    recorder wants exactly the event that preceded the crash).
    ``annotate=False`` skips the profiler annotation for spans on
    paths where jax may not be initialized yet.
    """
    ann = contextlib.nullcontext()
    if annotate:
        try:
            import jax

            ann = jax.profiler.TraceAnnotation(name)
        except Exception:  # pragma: no cover - profiler unavailable
            pass
    st = _current_stack()
    st.append(name)
    t0 = time.perf_counter()
    try:
        with ann:
            yield
    finally:
        dur = time.perf_counter() - t0
        st.pop()
        emit_span(
            name, dur, bus=bus, sink=sink, step=step, hist=hist,
            **fields,
        )
