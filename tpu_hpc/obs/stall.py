"""Rolling step-time watermark: distinguish *slow* from *hung*.

The hang watchdog (resilience/heartbeat.py) only knows binary
liveness: ticks or no ticks. A straggling host -- a chip thermally
throttling, a data loader degrading, DCN congestion -- ticks happily
while the fleet bleeds goodput, and the 100k-GPU operations
literature (arxiv 2510.20171) names exactly this gray failure as the
expensive one. :class:`StallDetector` keeps a rolling watermark of
recent step times and

* flags any step slower than ``factor`` x the watermark (a ``stall``
  event through the bus, so the report's restart timeline shows the
  degradation leading up to a watchdog kill), and
* feeds :meth:`heartbeat_extra` into the heartbeat file -- the
  supervisor (or an operator's ``cat``) then sees ``step_s`` next to
  the tick and can tell "wedged" from "3x slower than its own
  recent past" without attaching to the process.
"""
from __future__ import annotations

import collections
import statistics
from typing import Deque, Dict, Optional


class StallDetector:
    """Per-run step-time watermark. ``observe`` once per progress
    point with that point's per-step wall time."""

    def __init__(
        self,
        window: int = 32,
        factor: float = 3.0,
        min_samples: int = 5,
        bus=None,
    ):
        if factor <= 1.0:
            raise ValueError(f"factor {factor} must be > 1")
        if min_samples < 2:
            raise ValueError(f"min_samples {min_samples} must be >= 2")
        if window < min_samples:
            # The deque can never hold min_samples entries: the
            # detector would silently never warm up and never fire.
            raise ValueError(
                f"window {window} must be >= min_samples {min_samples}"
            )
        self.window = window
        self.factor = factor
        self.min_samples = min_samples
        self._bus = bus
        self._times: Deque[float] = collections.deque(maxlen=window)
        self.last_step: Optional[int] = None
        self.last_step_s: Optional[float] = None
        self.stalls = 0

    @property
    def watermark_s(self) -> Optional[float]:
        """Median of the recent window; None until warm."""
        if len(self._times) < self.min_samples:
            return None
        return statistics.median(self._times)

    def observe(
        self, step: int, step_s: float, sink: Optional[str] = None,
        trace_id: Optional[str] = None,
    ) -> Optional[dict]:
        """Record one step time; returns the stall info dict (and
        emits a ``stall`` event) when this step breached the
        watermark, else None. ``trace_id`` (obs/trace.py) correlates
        the stall with the step/tick trace it happened on -- the key
        an anomaly-triggered capture is filed under. The breaching
        sample still enters the window -- a run that *stays* slow
        re-baselines instead of alarming forever."""
        watermark = self.watermark_s
        info = None
        # A non-positive watermark carries no cadence to breach (a
        # window of zero-duration steps -- e.g. virtual-clock ticks
        # that did no metered work); treat it as not-warm rather than
        # dividing by it.
        if watermark is not None and watermark <= 0.0:
            watermark = None
        if watermark is not None and step_s > self.factor * watermark:
            self.stalls += 1
            info = {
                "step": step,
                "step_s": step_s,
                "watermark_s": watermark,
                "ratio": step_s / watermark,
            }
            from tpu_hpc.obs.events import get_bus

            (self._bus or get_bus()).emit(
                "stall", sink=sink, trace_id=trace_id, **info
            )
        self._times.append(step_s)
        self.last_step = step
        self.last_step_s = step_s
        return info

    def heartbeat_extra(self) -> Dict[str, float]:
        """Enrichment fields for Heartbeat.tick -- only what is known
        (an un-warmed detector contributes nothing rather than
        nulls)."""
        out: Dict[str, float] = {}
        if self.last_step_s is not None:
            out["step_s"] = round(self.last_step_s, 4)
        wm = self.watermark_s
        if wm is not None:
            out["watermark_s"] = round(wm, 4)
        return out

    def digest_extra(self) -> Dict[str, float]:
        """The digest-side twin of :meth:`heartbeat_extra`: the same
        normalized (step_s, watermark_s) signal exported into the
        health digest (obs/digest.py), so the fleet rollup's
        cross-host straggler comparison (obs/live.py) judges on
        exactly the numbers the supervisor already trusts from the
        heartbeat file -- one signal, two transports."""
        return self.heartbeat_extra()
