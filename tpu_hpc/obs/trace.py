"""End-to-end causal tracing: trace contexts, the critical-path
analyzer, and anomaly-triggered capture.

The obs spine (schema/events/spans) measures *totals*; this module
adds *causality*. Three pieces:

**Trace contexts.** A trace id is a run_id-scoped string
``"<run_id>:<kind>:<key>"`` -- ``req:r0042`` for a serving request,
``step:128`` for a training step, ``tick:N`` for a load-harness tick.
Because the id is a pure function of (run_id, kind, key) and run_id is
shared process-wide (``TPU_HPC_RUN_ID``), every host and every layer
derives the SAME id with zero coordination -- which is what lets
flight-ring dumps from different hosts merge into one timeline.
Producers either stamp ``trace_id`` explicitly (the lifecycle events)
or :func:`activate` a context around a call so everything emitted
inside -- engine spans, ``kv_block`` ring events, the disagg
``kv_transfer`` hop -- joins the trace ambiently (one thread-local
getattr per emit; the ring-only hot path stays cheap).

**Critical-path analyzer** (``python -m tpu_hpc.obs.trace run.jsonl``).
Reconstructs per-request and per-step timelines from run JSONL plus
any flight-recorder dumps, decomposes TTFT into attributed phases
(queue / prefill execution / prefill interleave wait / decode), names
the dominant phase at each latency quantile (the request *at* p50/p95/
p99, not an average -- "Performance Characterization of Distributed
Deep Learning Strategies", arxiv 2505.12832, argues attribution is
what makes a system tunable), does the same for training-step phase
spans, and exports a Chrome-trace / Perfetto JSON for visual
inspection. A span carrying a request trace id with no anchoring
lifecycle event is an **orphan** -- the analyzer counts them, and the
tests pin zero on a complete run.

**Anomaly-triggered capture** (:class:`AnomalyCapture`). When the
stall watermark trips, the numeric-health guard classifies a poisoned
step, or a loadgen SLO bound is breached, the capture controller
(armed by its owner: Trainer, LoadHarness) dumps the flight ring,
arms ONE bounded ``jax.profiler`` trace for the next N steps, records
the device-memory high-water mark, and emits a ``capture_triggered``
record keyed by the triggering trace_id -- closing the loop from
symptom to evidence with zero operator intervention (the fleet-scale
diagnosability requirement of arxiv 2510.20171). Captures are
one-shot by default: an anomaly storm must yield one clean evidence
bundle, not a disk full of overlapping traces.
"""
from __future__ import annotations

import argparse
import contextlib
import dataclasses
import glob
import json
import os
import re
import sys
import time
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

from tpu_hpc.obs import events as events_mod
from tpu_hpc.obs.events import EventBus, get_bus
from tpu_hpc.obs.quantiles import quantile
from tpu_hpc.obs.schema import (
    SCHEMA_VERSION,
    SchemaError,
    load_records,
)

# Trace kinds with a meaning the analyzer knows how to reconstruct.
KIND_REQUEST = "req"
KIND_STEP = "step"
KIND_TICK = "tick"

# Scheduler-emitted spans whose durations are THIS request's own
# prefill execution (meter-clock, depth 0); everything else of the
# admit->first-token window is interleave/scheduling wait.
_PREFILL_EXEC_SPANS = ("prefill_chunk", "admit")
# Decode-side spans the ITL attribution splits shares over.
_DECODE_SIDE_SPANS = (
    "decode", "spec_draft", "spec_verify", "spec_draft_prefill",
    "colocated_train_step", "kv_transfer",
)


# -- trace contexts ----------------------------------------------------
@dataclasses.dataclass(frozen=True)
class TraceContext:
    """One trace's identity plus its birth clocks. ``t_mono`` /
    ``t_wall`` anchor the monotonic timeline against wall time for
    cross-host alignment; durations always come from the monotonic
    clock (the spans.py contract)."""

    trace_id: str
    kind: str
    key: str
    t_wall: float
    t_mono: float
    parent: Optional[str] = None


def trace_id_for(
    kind: str, key, run_id: Optional[str] = None,
    bus: Optional[EventBus] = None,
) -> str:
    """The canonical derived id: ``<run_id>:<kind>:<key>``. Pure in
    (run_id, kind, key), so every layer/host that knows the key
    derives the same id without a registry."""
    run = run_id or (bus or get_bus()).run_id
    return f"{run}:{kind}:{key}"


def request_trace_id(rid: str, run_id: Optional[str] = None) -> str:
    return trace_id_for(KIND_REQUEST, rid, run_id=run_id)


def step_trace_id(step: int, run_id: Optional[str] = None) -> str:
    return trace_id_for(KIND_STEP, int(step), run_id=run_id)


def parse_trace_id(trace_id: str) -> Tuple[Optional[str], str, str]:
    """``(run_id, kind, key)``; run_id None when the id is not in the
    canonical 3-part form (run ids never contain ':', so splitting
    from the right is unambiguous even for exotic run id spellings)."""
    parts = trace_id.rsplit(":", 2)
    if len(parts) == 3:
        return parts[0], parts[1], parts[2]
    return None, "", trace_id


def new_context(
    kind: str, key, parent: Optional[str] = None,
    run_id: Optional[str] = None, bus: Optional[EventBus] = None,
) -> TraceContext:
    return TraceContext(
        trace_id=trace_id_for(kind, key, run_id=run_id, bus=bus),
        kind=kind, key=str(key),
        t_wall=time.time(), t_mono=time.perf_counter(),
        parent=parent,
    )


def announce(
    ctx: TraceContext,
    *,
    tenant: Optional[str] = None,
    sink: Optional[str] = None,
    bus: Optional[EventBus] = None,
) -> dict:
    """Emit the ``trace_ctx`` birth record for ``ctx`` -- the anchor
    the analyzer joins later spans/events against."""
    return (bus or get_bus()).emit(
        "trace_ctx",
        sink=sink,
        trace_id=ctx.trace_id,
        kind=ctx.kind,
        key=ctx.key,
        tenant=tenant,
        parent=ctx.parent,
        t_wall=ctx.t_wall,
        t_mono=ctx.t_mono,
    )


@contextlib.contextmanager
def activate(ctx) -> Iterator[None]:
    """Make ``ctx`` (a TraceContext or a bare trace id string) the
    thread's ambient trace: every bus emit inside the block that does
    not carry an explicit ``trace_id`` is stamped with it. Nests --
    the previous ambient trace is restored on exit."""
    tid = ctx.trace_id if isinstance(ctx, TraceContext) else ctx
    prev = getattr(events_mod._TRACE, "trace_id", None)
    events_mod._TRACE.trace_id = tid
    try:
        yield
    finally:
        events_mod._TRACE.trace_id = prev


# -- anomaly-triggered capture ----------------------------------------
class AnomalyCapture:
    """Symptom -> evidence, automatically.

    ``trigger(reason, trace_id=...)`` (called by the stall watermark,
    the guard's poisoned verdict, or a loadgen SLO breach) dumps the
    flight ring, arms one bounded ``jax.profiler`` trace covering the
    next ``n_steps`` steps (via profiling/profiler.TrainingProfiler),
    records the device-memory high-water mark, and emits a
    ``capture_triggered`` record correlating all of it by the
    triggering trace_id. The owner advances the bounded window with
    :meth:`step` and MUST :meth:`close` at run end (an open profiler
    trace otherwise leaks for the life of the process).

    One-shot by default (``max_captures=1``): exactly one evidence
    bundle per run unless the owner re-arms. Capture is diagnostics --
    every failure inside it is swallowed so a dying run's last act is
    never a new crash (the dump_flight contract).
    """

    def __init__(
        self,
        profile_dir: str,
        n_steps: int = 2,
        max_captures: int = 1,
        bus: Optional[EventBus] = None,
    ):
        if n_steps < 1:
            raise ValueError(f"n_steps {n_steps} must be >= 1")
        if max_captures < 1:
            raise ValueError(
                f"max_captures {max_captures} must be >= 1"
            )
        self.profile_dir = profile_dir
        self.n_steps = n_steps
        self.max_captures = max_captures
        self._bus = bus
        # Lifetime count: also names the per-capture profiler dirs
        # (capture<N>), so a rearm NEVER re-numbers into a previous
        # bundle's directory -- the non-clobbering flight-dump
        # discipline applied to profiler output.
        self.captures = 0
        # Budget window: captures since the last rearm.
        self._window_used = 0
        self.last: Optional[dict] = None
        self._prof = None

    @property
    def armed(self) -> bool:
        return self._window_used < self.max_captures

    def rearm(self) -> None:
        """Allow another capture (a long-running service that has
        already shipped the previous evidence bundle). The lifetime
        counter keeps numbering, so the next bundle's profiler dir
        never overwrites an earlier one."""
        self._window_used = 0

    def trigger(
        self,
        reason: str,
        trace_id: Optional[str] = None,
        step: Optional[int] = None,
        sink: Optional[str] = None,
        arm_profiler: bool = True,
    ) -> Optional[dict]:
        """Fire one capture; returns the ``capture_triggered`` record,
        or None when the budget is spent (an anomaly storm re-triggers
        every tick -- only the first gets the evidence bundle).
        ``arm_profiler=False`` collects the flight dump + memory
        snapshot only -- for post-run triggers (an SLO breach at
        summary time) where no future steps exist to bound (or ever
        close) a profiler window."""
        if not self.armed:
            return None
        self.captures += 1
        self._window_used += 1
        bus = self._bus or get_bus()
        # The trace key rides in the dump filename so on-disk evidence
        # is greppable by request/step even before the JSONL is read.
        key = parse_trace_id(trace_id)[2] if trace_id else ""
        full_reason = f"capture.{reason}" + (f".{key}" if key else "")
        path = None
        if not bus.flight_dir:
            # The capture contract promises flight evidence under the
            # capture dir even when no TPU_HPC_FLIGHT_DIR is armed --
            # an unconfigured bus must not silently drop the dump.
            safe = re.sub(r"[^A-Za-z0-9_.-]", "_", full_reason)
            path = os.path.join(
                self.profile_dir,
                f"flight.{safe}.pid{os.getpid()}.jsonl",
            )
        flight_path = bus.dump_flight(full_reason, path=path)
        prof_dir = self._arm_profiler(step) if arm_profiler else None
        self._emit_device_memory(sink)
        self.last = bus.emit(
            "capture_triggered",
            sink=sink,
            reason=reason,
            trace_id=trace_id,
            step=step,
            n_steps=self.n_steps if prof_dir else 0,
            profile_dir=prof_dir,
            flight_path=flight_path,
        )
        return self.last

    def _arm_profiler(self, step: Optional[int]) -> Optional[str]:
        try:
            from tpu_hpc.profiling import TrainingProfiler

            base = int(step or 0)
            log_dir = os.path.join(
                self.profile_dir, f"capture{self.captures}"
            )
            prof = TrainingProfiler(
                log_dir=log_dir, start_step=base,
                num_steps=self.n_steps,
            )
            prof.step(base)  # opens the trace NOW
            if prof.active:
                self._prof = prof
                return log_dir
        except Exception:  # pragma: no cover - profiler busy/absent
            pass
        return None

    def _emit_device_memory(self, sink: Optional[str]) -> None:
        try:
            from tpu_hpc.profiling import device_memory_summary

            device_memory_summary(emit=True, sink=sink)
        except Exception:  # pragma: no cover - no allocator stats
            pass

    def step(self, step: int) -> None:
        """Advance the bounded profiler window; closes the trace once
        ``n_steps`` steps have passed since the trigger. Like every
        other capture path, failures are swallowed: a disk filling up
        while the trace flushes (likely during exactly the anomaly
        under capture) must not crash the run being diagnosed."""
        prof = self._prof
        if prof is None:
            return
        try:
            prof.step(int(step))
        except Exception:  # pragma: no cover - stop_trace I/O error
            self._prof = None
        else:
            if not prof.active:
                self._prof = None

    def close(self) -> None:
        """Stop any still-open capture trace (run teardown)."""
        if self._prof is not None:
            try:
                self._prof.stop()
            except Exception:  # pragma: no cover - disk-full teardown
                pass
            self._prof = None


# -- timeline reconstruction ------------------------------------------
@dataclasses.dataclass
class RequestTrace:
    """One request's reconstructed lifecycle (all times in ms on the
    meter clock, relative to its own submission)."""

    trace_id: str
    rid: str
    tenant: str = "default"
    arrival_ms: Optional[float] = None
    queue_ms: Optional[float] = None
    ttft_ms: Optional[float] = None
    total_ms: Optional[float] = None
    tokens: Optional[int] = None
    shed: Optional[str] = None
    anchored: bool = False
    itl_ms: List[float] = dataclasses.field(default_factory=list)
    # (name, dur_ms, depth)
    spans: List[Tuple[str, float, int]] = dataclasses.field(
        default_factory=list
    )

    @property
    def complete(self) -> bool:
        return self.ttft_ms is not None and self.total_ms is not None

    def phases(self) -> Dict[str, float]:
        """TTFT + decode decomposition into named phases. ``prefill``
        is execution attributable to this request's own admission/
        chunk work (the scheduler's meter-clock spans);
        ``prefill_wait`` is the remainder of the admit->first-token
        window -- interleaved other-request work and scheduling."""
        out: Dict[str, float] = {}
        if self.ttft_ms is None:
            return out
        queue = max(float(self.queue_ms or 0.0), 0.0)
        out["queue"] = min(queue, self.ttft_ms)
        window = max(self.ttft_ms - out["queue"], 0.0)
        exec_ms = sum(
            ms for name, ms, depth in self.spans
            if name in _PREFILL_EXEC_SPANS and depth == 0
        )
        out["prefill"] = min(exec_ms, window)
        out["prefill_wait"] = window - out["prefill"]
        if self.total_ms is not None:
            out["decode"] = max(self.total_ms - self.ttft_ms, 0.0)
        return out

    def ttft_breakdown(self) -> dict:
        """Phase shares of THIS request's TTFT plus the dominant
        phase -- the per-quantile critical-path row."""
        phases = {
            k: v for k, v in self.phases().items() if k != "decode"
        }
        ttft = self.ttft_ms or 0.0
        attributed = sum(phases.values())
        shares = {
            k: (v / ttft if ttft > 0 else 0.0)
            for k, v in phases.items()
        }
        dominant = (
            max(phases, key=phases.get) if phases else None
        )
        return {
            "rid": self.rid,
            "tenant": self.tenant,
            "ttft_ms": ttft,
            "phases_ms": phases,
            "shares": shares,
            "dominant": dominant,
            "attributed": (
                attributed / ttft if ttft > 0 else 1.0
            ),
        }


@dataclasses.dataclass
class StepTrace:
    """One training step/chunk's phase spans (wall-derived durations
    measured on the monotonic clock)."""

    trace_id: str
    step: int
    spans: List[Tuple[str, float, int]] = dataclasses.field(
        default_factory=list
    )
    stalls: int = 0

    @property
    def wall_ms(self) -> float:
        return sum(ms for _, ms, depth in self.spans if depth == 0)

    def phases(self) -> Dict[str, float]:
        out: Dict[str, float] = {}
        for name, ms, depth in self.spans:
            if depth == 0:
                out[name] = out.get(name, 0.0) + ms
        return out

    def breakdown(self) -> dict:
        phases = self.phases()
        wall = self.wall_ms
        dominant = max(phases, key=phases.get) if phases else None
        return {
            "step": self.step,
            "wall_ms": wall,
            "phases_ms": phases,
            "shares": {
                k: (v / wall if wall > 0 else 0.0)
                for k, v in phases.items()
            },
            "dominant": dominant,
        }


_LIFECYCLE_ANCHORS = (
    "trace_ctx", "lg_arrival", "lg_admit", "lg_first_token",
    "lg_finish", "lg_shed", "request",
)


def build_traces(records: Sequence[dict]) -> dict:
    """Group records by trace_id into request/step timelines.

    Returns ``{"requests": {tid: RequestTrace}, "steps":
    {tid: StepTrace}, "orphan_spans": int, "captures": [...]}`` --
    an orphan is a span carrying a request-kind trace id that no
    lifecycle event ever anchored (a propagation bug: some layer
    stamped an id nothing else knows about)."""
    requests: Dict[str, RequestTrace] = {}
    steps: Dict[str, StepTrace] = {}
    captures: List[dict] = []
    orphans = 0

    def req(tid: str, key: str) -> RequestTrace:
        rt = requests.get(tid)
        if rt is None:
            rt = requests[tid] = RequestTrace(trace_id=tid, rid=key)
        return rt

    for r in records:
        event = r.get("event")
        if event == "capture_triggered":
            captures.append(r)
            continue
        tid = r.get("trace_id")
        if not tid:
            continue
        _, kind, key = parse_trace_id(tid)
        if kind == KIND_REQUEST:
            rt = req(tid, key)
            if "tenant" in r:
                rt.tenant = r["tenant"]
            if event in _LIFECYCLE_ANCHORS:
                rt.anchored = True
            if event == "lg_arrival":
                rt.arrival_ms = float(r["arrival_ms"])
            elif event == "lg_admit":
                rt.queue_ms = float(r["queue_ms"])
            elif event == "lg_first_token":
                rt.ttft_ms = float(r["ttft_ms"])
            elif event == "lg_token" and "itl_ms" in r:
                rt.itl_ms.append(float(r["itl_ms"]))
            elif event == "lg_finish":
                rt.total_ms = float(r["total_ms"])
                rt.tokens = int(r["tokens"])
            elif event == "lg_shed":
                rt.shed = r.get("reason") or "shed"
            elif event == "request":
                # The plain ServeMeter path (non-loadgen replays).
                rt.queue_ms = float(r["queue_ms"])
                rt.ttft_ms = float(r["ttft_ms"])
                rt.total_ms = float(r["total_ms"])
                rt.tokens = int(r["tokens"])
                rt.anchored = True
            elif event == "span":
                rt.spans.append((
                    r["name"], 1e3 * float(r["dur_s"]),
                    int(r.get("depth") or 0),
                ))
        elif kind in (KIND_STEP, KIND_TICK):
            st = steps.get(tid)
            if st is None:
                try:
                    stepno = int(key)
                except ValueError:
                    stepno = -1
                st = steps[tid] = StepTrace(trace_id=tid, step=stepno)
            if event == "span":
                st.spans.append((
                    r["name"], 1e3 * float(r["dur_s"]),
                    int(r.get("depth") or 0),
                ))
            elif event == "stall":
                st.stalls += 1
        elif event == "span":
            # A span with an unparseable trace id can be attributed to
            # nothing -- that is exactly what the orphan count flags.
            orphans += 1

    orphans += sum(
        len(rt.spans) for rt in requests.values() if not rt.anchored
    )
    return {
        "requests": requests,
        "steps": steps,
        "orphan_spans": orphans,
        "captures": captures,
    }


# -- critical-path analysis -------------------------------------------
_QUANTILES = (("p50", 0.50), ("p95", 0.95), ("p99", 0.99))


def _at_quantile(sorted_items: list, q: float):
    """Nearest-rank pick: the actual item AT the quantile, so the
    decomposition describes a real request/step, not an average."""
    if not sorted_items:
        return None
    idx = min(
        len(sorted_items) - 1,
        max(0, int(round(q * (len(sorted_items) - 1)))),
    )
    return sorted_items[idx]


def _analyze_requests(requests: Dict[str, RequestTrace],
                      records: Sequence[dict]) -> Optional[dict]:
    if not requests:
        return None
    done = sorted(
        (rt for rt in requests.values() if rt.complete),
        key=lambda rt: rt.ttft_ms,
    )
    shed = sum(1 for rt in requests.values() if rt.shed)
    phase_totals: Dict[str, float] = {}
    for rt in requests.values():
        for k, v in rt.phases().items():
            phase_totals[k] = phase_totals.get(k, 0.0) + v
    ttfts = [rt.ttft_ms for rt in done]
    out: dict = {
        "count": len(requests),
        "complete": len(done),
        "shed": shed,
        "phase_totals_ms": {
            k: round(v, 3) for k, v in sorted(phase_totals.items())
        },
        "ttft_ms": {
            name: quantile(ttfts, q) for name, q in _QUANTILES
        },
        "ttft_critical_path": {
            name: rt.ttft_breakdown()
            for name, q in _QUANTILES
            if (rt := _at_quantile(done, q)) is not None
        },
    }
    # ITL: quantiles from the closing serve_summary when present
    # (lg_token is ring-only by design), else rebuilt from whatever
    # per-token evidence a flight dump carried.
    summaries = [
        r for r in records if r.get("event") == "serve_summary"
    ]
    itls: List[float] = []
    for rt in requests.values():
        itls.extend(rt.itl_ms)
    itl_q = None
    if summaries:
        s = summaries[-1]
        itl_q = {
            name: s[f"itl_ms_{name}"]
            for name, _ in _QUANTILES if f"itl_ms_{name}" in s
        }
    elif itls:
        itls.sort()
        itl_q = {name: quantile(itls, q) for name, q in _QUANTILES}
    if itl_q is not None:
        out["itl_ms"] = itl_q
        # Decode-window attribution is batch-level (one decode step
        # serves every slot), so shares come from the decode-side
        # span totals rather than per-gap evidence.
        decode_spans: Dict[str, float] = {}
        for r in records:
            if (
                r.get("event") == "span"
                and r.get("name") in _DECODE_SIDE_SPANS
                and not r.get("depth")
            ):
                decode_spans[r["name"]] = (
                    decode_spans.get(r["name"], 0.0)
                    + 1e3 * float(r["dur_s"])
                )
        total = sum(decode_spans.values())
        out["itl_attribution"] = {
            "shares": {
                k: (v / total if total > 0 else 0.0)
                for k, v in sorted(decode_spans.items())
            },
            "dominant": (
                max(decode_spans, key=decode_spans.get)
                if decode_spans else None
            ),
        }
    return out


def _analyze_steps(steps: Dict[str, StepTrace]) -> Optional[dict]:
    timed = sorted(
        (st for st in steps.values() if st.spans),
        key=lambda st: st.wall_ms,
    )
    if not timed:
        return None
    walls = [st.wall_ms for st in timed]
    phase_totals: Dict[str, float] = {}
    for st in timed:
        for k, v in st.phases().items():
            phase_totals[k] = phase_totals.get(k, 0.0) + v
    total = sum(phase_totals.values())
    return {
        "count": len(timed),
        "stalls": sum(st.stalls for st in steps.values()),
        "wall_ms": {
            name: quantile(walls, q) for name, q in _QUANTILES
        },
        "phase_totals_ms": {
            k: round(v, 3) for k, v in sorted(phase_totals.items())
        },
        "shares": {
            k: (v / total if total > 0 else 0.0)
            for k, v in sorted(phase_totals.items())
        },
        "critical_path": {
            name: st.breakdown()
            for name, q in _QUANTILES
            if (st := _at_quantile(timed, q)) is not None
        },
    }


def analyze(records: Sequence[dict]) -> dict:
    """The full critical-path report over one merged record set (run
    JSONL + any flight dumps) -- the ``--json`` object."""
    traces = build_traces(records)
    return {
        "schema_version": SCHEMA_VERSION,
        "run_id": next(
            (r["run_id"] for r in records if "run_id" in r), None
        ),
        "n_records": len(records),
        "orphan_spans": traces["orphan_spans"],
        "requests": _analyze_requests(traces["requests"], records),
        "steps": _analyze_steps(traces["steps"]),
        "captures": [
            {
                k: c.get(k)
                for k in ("reason", "trace_id", "step", "n_steps",
                          "profile_dir", "flight_path")
            }
            for c in traces["captures"]
        ],
    }


# -- Chrome-trace / Perfetto export -----------------------------------
def chrome_trace(records: Sequence[dict]) -> dict:
    """Chrome trace-event JSON (chrome://tracing, Perfetto's legacy
    importer). Request rows are laid out on the meter clock (each
    request relative to its own arrival); training spans on the
    monotonic clock (``t_mono``), both in microseconds."""
    traces = build_traces(records)
    ev: List[dict] = []
    ev.append({
        "ph": "M", "pid": 1, "name": "process_name",
        "args": {"name": "serve requests (meter-clock ms)"},
    })
    ev.append({
        "ph": "M", "pid": 2, "name": "process_name",
        "args": {"name": "train/tick spans (monotonic clock)"},
    })
    reqs = sorted(
        traces["requests"].values(),
        key=lambda rt: (rt.arrival_ms or 0.0, rt.rid),
    )
    for tid_row, rt in enumerate(reqs, start=1):
        base = (rt.arrival_ms or 0.0) * 1e3  # us
        ev.append({
            "ph": "M", "pid": 1, "tid": tid_row,
            "name": "thread_name", "args": {"name": rt.rid},
        })
        common = {
            "pid": 1, "tid": tid_row,
            "args": {"trace_id": rt.trace_id, "tenant": rt.tenant},
        }
        if rt.shed:
            ev.append({
                "ph": "i", "name": f"shed:{rt.shed}", "ts": base,
                "s": "t", **common,
            })
            continue
        phases = rt.phases()
        t = base
        for name in ("queue", "prefill", "prefill_wait", "decode"):
            dur = phases.get(name)
            if dur is None:
                continue
            ev.append({
                "ph": "X", "name": name, "ts": t, "dur": dur * 1e3,
                **common,
            })
            t += dur * 1e3
    # Training/tick spans on the monotonic axis, normalized to the
    # earliest t_mono seen so the file starts near zero.
    monos = [
        r.get("t_mono") for r in records
        if r.get("event") == "span" and r.get("t_mono") is not None
    ]
    t0 = min(monos) if monos else 0.0
    for r in records:
        if r.get("event") != "span" or not r.get("trace_id"):
            continue
        _, kind, _ = parse_trace_id(r["trace_id"])
        if kind not in (KIND_STEP, KIND_TICK):
            continue
        dur_us = 1e6 * float(r["dur_s"])
        end = r.get("t_mono")
        ts = (end - t0) * 1e6 - dur_us if end is not None else 0.0
        ev.append({
            "ph": "X", "pid": 2, "tid": 1 + int(r.get("depth") or 0),
            "name": r["name"], "ts": max(ts, 0.0), "dur": dur_us,
            "args": {"trace_id": r["trace_id"],
                     "step": r.get("step")},
        })
    for c in traces["captures"]:
        ev.append({
            "ph": "i", "pid": 2, "tid": 1, "s": "g", "ts": 0.0,
            "name": f"capture:{c.get('reason')}",
            "args": {"trace_id": c.get("trace_id"),
                     "flight_path": c.get("flight_path")},
        })
    return {"traceEvents": ev, "displayTimeUnit": "ms"}


# -- rendering ---------------------------------------------------------
def format_analysis(rep: dict) -> str:
    lines = [
        f"# tpu_hpc trace report -- run_id {rep['run_id'] or '(none)'}"
        f" ({rep['n_records']} records)",
        "",
        f"orphan spans: {rep['orphan_spans']}"
        + (" (complete trace)" if not rep["orphan_spans"] else
           "  <-- propagation gap: spans whose trace no lifecycle "
           "event anchors"),
    ]
    req = rep.get("requests")
    if req:
        lines += [
            "",
            "## Requests -- TTFT critical path",
            "",
            f"{req['complete']}/{req['count']} complete, "
            f"{req['shed']} shed",
            "",
            "| quantile | TTFT (ms) | rid | decomposition | "
            "dominant | attributed |",
            "|---|---|---|---|---|---|",
        ]
        for name, _ in _QUANTILES:
            cp = (req.get("ttft_critical_path") or {}).get(name)
            if cp is None:
                continue
            decomp = " + ".join(
                f"{k} {v:.1f}" for k, v in cp["phases_ms"].items()
            )
            lines.append(
                f"| {name} | {cp['ttft_ms']:.1f} | {cp['rid']} | "
                f"{decomp} | **{cp['dominant']}** "
                f"({cp['shares'].get(cp['dominant'], 0.0):.0%}) | "
                f"{cp['attributed']:.0%} |"
            )
        if "itl_ms" in req:
            itl = req["itl_ms"]
            att = req.get("itl_attribution") or {}
            lines += [
                "",
                "ITL p50/p95/p99: "
                + " / ".join(
                    f"{itl.get(n, 0.0):.1f}" for n, _ in _QUANTILES
                )
                + " ms"
                + (
                    f" -- decode window dominated by "
                    f"**{att['dominant']}**"
                    if att.get("dominant") else ""
                ),
            ]
    steps = rep.get("steps")
    if steps:
        lines += [
            "",
            "## Training steps -- phase critical path",
            "",
            f"{steps['count']} step trace(s), {steps['stalls']} "
            "stall event(s); phase shares: "
            + ", ".join(
                f"{k} {v:.0%}" for k, v in steps["shares"].items()
            ),
            "",
            "| quantile | step wall (ms) | step | dominant |",
            "|---|---|---|---|",
        ]
        for name, _ in _QUANTILES:
            cp = (steps.get("critical_path") or {}).get(name)
            if cp is None:
                continue
            lines.append(
                f"| {name} | {cp['wall_ms']:.1f} | {cp['step']} | "
                f"**{cp['dominant']}** "
                f"({cp['shares'].get(cp['dominant'], 0.0):.0%}) |"
            )
    caps = rep.get("captures") or []
    if caps:
        lines += ["", "## Anomaly captures", ""]
        for c in caps:
            lines.append(
                f"- {c['reason']} (trace {c['trace_id']}): profiler "
                f"-> {c['profile_dir'] or '(unavailable)'}, flight "
                f"-> {c['flight_path'] or '(no flight dir)'}"
            )
    return "\n".join(lines) + "\n"


def _load_all(
    paths: Sequence[str],
    flight_dir: Optional[str],
    validate: bool,
) -> list:
    files = list(paths)
    if flight_dir:
        files += sorted(
            glob.glob(os.path.join(flight_dir, "flight.*.jsonl*"))
        )
    # Exact-duplicate records are dropped across the merge: the bus
    # writes ONE stamped record to both the sink and the flight ring,
    # so any dump taken during a sinked run overlaps the run log --
    # loading both copies would double every span duration and skew
    # every quantile. Two distinct emissions are never identical
    # (each carries its own wall-clock stamp), so full-record
    # equality is the correct identity.
    records: list = []
    seen = set()
    for p in files:
        for rec in load_records(p, validate=validate):
            key = json.dumps(rec, sort_keys=True)
            if key not in seen:
                seen.add(key)
                records.append(rec)
    return records


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="tpu_hpc.obs.trace",
        description=__doc__.split("\n")[0],
    )
    ap.add_argument(
        "paths", nargs="+",
        help="run JSONL file(s) (run log, serve/loadgen trace, "
        "flight dumps) -- merged by trace_id",
    )
    ap.add_argument(
        "--flight-dir", default=None,
        help="also merge every flight.*.jsonl dump in this directory",
    )
    ap.add_argument("--json", action="store_true",
                    help="emit the analysis as one JSON object")
    ap.add_argument(
        "--chrome", default=None, metavar="PATH",
        help="write a Chrome-trace/Perfetto JSON timeline to PATH",
    )
    ap.add_argument(
        "--no-validate", action="store_true",
        help="skip schema validation (salvage partially-corrupt logs)",
    )
    args = ap.parse_args(argv)
    try:
        records = _load_all(
            args.paths, args.flight_dir, validate=not args.no_validate
        )
    except OSError as e:
        print(f"tpu_hpc.obs.trace: {e}", file=sys.stderr)
        return 2
    except SchemaError as e:
        print(
            f"tpu_hpc.obs.trace: schema error: {e}", file=sys.stderr
        )
        return 2
    if not records:
        print(
            "tpu_hpc.obs.trace: no records in "
            + ", ".join(args.paths),
            file=sys.stderr,
        )
        return 2
    rep = analyze(records)
    if args.chrome:
        parent = os.path.dirname(args.chrome)
        if parent:
            os.makedirs(parent, exist_ok=True)
        with open(args.chrome, "w") as f:
            json.dump(chrome_trace(records), f)
    if args.json:
        print(json.dumps(rep))
    else:
        print(format_analysis(rep), end="")
    return 0


if __name__ == "__main__":
    sys.exit(main())
