"""The one record schema every telemetry sink speaks.

Before this module, three subsystems emitted JSONL with three ad-hoc
shapes: the Trainer's run log (train/trainer.py), the serving meter
(serve/metrics.py), and bench.py's record lines. A consumer (the
goodput report, a dashboard, the driver) had to know which producer
wrote which file. Now every record carries ``schema_version`` and an
``event`` kind with a declared field contract, and one validator
covers all of them -- the "structured events with a schema" discipline
the fleet-scale observability literature treats as table stakes
(arxiv 2510.20171's attribution pipelines start from exactly this).

Contract:

* every record is a flat-ish JSON object with ``schema_version``,
  ``event`` and ``time`` (wall clock, seconds);
* ``run_id`` / ``host`` / ``pid`` / ``attempt`` / ``step`` are common
  optional provenance fields (the event bus stamps the first three);
* each event kind declares required fields plus either a closed set of
  optional fields or ``open=True`` (kinds that carry user-named aux
  metrics -- eval records, serve summaries, bench rows);
* :func:`validate_record` / :func:`validate_file` fail loudly on an
  unknown kind, a missing required field, or (for closed kinds) an
  unknown field -- a producer drifting off-schema breaks a test, not
  a dashboard three weeks later.
"""
from __future__ import annotations

import dataclasses
import json
import time
from typing import Dict, Mapping, Tuple

SCHEMA_VERSION = 1

# Stamped on every record.
COMMON_REQUIRED: Tuple[str, ...] = ("schema_version", "event", "time")
# Provenance fields any record may carry. ``trace_id`` is the causal
# join key (obs/trace.py): "<run_id>:<kind>:<key>" ties a request's
# admit/prefill/token lifecycle (or a training step's phase spans)
# into one correlated record across every host's sink and flight
# ring. ``t_mono`` is a monotonic-clock timestamp (time.perf_counter)
# next to the wall-clock ``time``: cross-host trace merges order and
# measure on the monotonic clock (NTP skew cannot reorder a host
# against itself) and keep wall time for coarse alignment only.
COMMON_OPTIONAL: Tuple[str, ...] = (
    "run_id", "host", "pid", "attempt", "step", "seq",
    "trace_id", "t_mono",
)

# The canonical span-name table: every ``span(name)`` /
# ``emit_span(name)`` call site in the tree must use a name registered
# here (pinned by the tier-1 lint test in tests/test_trace.py), so
# span names cannot silently drift into an unbounded namespace as
# subsystems grow -- the report's phase table and the critical-path
# analyzer key on exactly these.
SPANS: Dict[str, str] = {
    "admit": "paged admission: page reservation + prefix-trie lookup "
             "(+ the disagg KV-plan warm)",
    "ckpt": "checkpoint save (sync or async dispatch)",
    "colocated_train_step": "loadgen colocation: a training step "
                            "stealing the chip from serving",
    "compute": "training forward/backward/update (fused chunk)",
    "data": "host-side batch generation (host-fed path only)",
    "decode": "one batched decode step (all slots)",
    "digest_publish": "health-digest build + append to the per-process "
                      "digest channel (obs/digest.py)",
    "elastic_reshard": "cross-topology restore reshard",
    "kv_transfer": "disagg prefill->decode KV hop",
    "morph": "live topology transition: quiesce -> reshard -> "
             "rebuild -> resume (tpu_hpc.elastic)",
    "prefill": "one prompt prefill forward (slab whole-prompt or one "
               "paged chunk)",
    "prefill_chunk": "scheduler-level per-request prefill advance "
                     "(meter-clock duration, trace-tagged)",
    "reshard": "bounded cross-sharding reshard execution",
    "restore": "checkpoint restore",
    "spec_draft": "speculative draft-model burst (k steps)",
    "spec_draft_prefill": "draft-model prompt prefill",
    "spec_verify": "speculative (k+1)-position verify forward",
    "warmup": "AOT executable-table warmup",
}


class SchemaError(ValueError):
    """A record violates the telemetry schema."""


@dataclasses.dataclass(frozen=True)
class EventSpec:
    """Field contract for one event kind. ``open=True`` permits extra
    fields (kinds that carry user-named metrics); closed kinds reject
    anything outside required+optional+common."""

    required: Tuple[str, ...]
    optional: Tuple[str, ...] = ()
    open: bool = False


EVENTS: Dict[str, EventSpec] = {
    # -- training run log (train/trainer.py) --
    "run_start": EventSpec((
        "start_step", "total_steps", "n_devices", "n_processes",
        "device_kind", "jax_version", "config",
    )),
    "epoch": EventSpec(
        ("epoch", "step", "loss", "items_per_s",
         "items_per_s_per_device", "s_per_step"),
        optional=("grad_norm",),
    ),
    "eval": EventSpec(("step", "n_steps", "loss"), open=True),
    "run_end": EventSpec((
        "step", "preempted", "attempt", "resumed_from_step", "goodput",
    ), optional=("rolled_back",)),
    # -- the telemetry spine itself (obs/) --
    "span": EventSpec(
        ("name", "dur_s"),
        optional=("parent", "depth", "n", "tier", "slot"),
    ),
    "metrics": EventSpec(("metrics",)),
    "stall": EventSpec(("step", "step_s", "watermark_s", "ratio")),
    # -- causal tracing (obs/trace.py) --
    # One record per trace birth (a request entering the scheduler):
    # announces the trace_id every later lifecycle event/span will
    # carry, with both clocks so cross-host merges can anchor the
    # monotonic timeline against wall time.
    "trace_ctx": EventSpec(
        ("trace_id", "kind", "key"),
        optional=("t_wall", "tenant", "parent"),
    ),
    # Anomaly-triggered capture (obs/trace.py AnomalyCapture): the
    # symptom->evidence record -- what tripped, which trace_id it is
    # keyed to, and where the bounded profiler trace + flight dump
    # landed.
    "capture_triggered": EventSpec(
        ("reason",),
        optional=("n_steps", "profile_dir", "flight_path"),
    ),
    # Per-device HBM high-water marks (profiling/profiler.py
    # device_memory_summary) -- was logger-only; the report's memory
    # section and the regress gate read exactly this.
    "device_memory": EventSpec(
        ("hbm_peak_bytes",),
        optional=(
            "n_devices", "hbm_in_use_bytes", "hbm_limit_bytes",
            "per_device",
        ),
    ),
    "fault": EventSpec(("kind",)),
    "flight_dump": EventSpec(("reason", "n_events")),
    # -- serving (serve/metrics.py) --
    "request": EventSpec(
        ("rid", "ttft_ms", "queue_ms", "tokens", "total_ms"),
    ),
    "serve_summary": EventSpec(
        ("requests", "tokens", "wall_s", "tokens_per_s",
         "tokens_per_s_per_chip", "ttft_ms_p50", "ttft_ms_p95",
         "itl_ms_p50", "itl_ms_p95", "prefill_tokens"),
        open=True,
    ),
    # -- bench.py record lines (metric/value/unit + workload extras) --
    "bench": EventSpec(("metric", "value", "unit"), open=True),
    # -- load generator (tpu_hpc/loadgen): one event per request
    #    lifecycle edge, so the report and the regress gate can
    #    reconstruct queueing/shedding behavior per tenant class --
    "load_scenario": EventSpec(
        ("scenario", "seed", "n_requests"), open=True,
    ),
    "lg_arrival": EventSpec(
        ("rid", "tenant", "arrival_ms"),
        optional=("prompt_len", "max_new_tokens", "priority"),
    ),
    "lg_admit": EventSpec(
        ("rid", "tenant", "queue_ms"),
        optional=("prefill_tokens", "queued"),
    ),
    "lg_first_token": EventSpec(("rid", "tenant", "ttft_ms")),
    # Per-token cadence evidence; hot path, so producers usually emit
    # it ring-only (flight-recorder forensics) rather than to the sink.
    "lg_token": EventSpec(("rid",), optional=("itl_ms",)),
    "lg_finish": EventSpec(("rid", "tenant", "tokens", "total_ms")),
    "lg_shed": EventSpec(("rid", "tenant", "reason")),
    # -- admission-control decisions (serve/scheduler.py policy) --
    "admission": EventSpec(
        ("action", "occupancy"),
        optional=("rid", "tenant", "reason", "pending", "by_tenant"),
    ),
    # -- speculative decoding (serve/spec.py): one record per verify
    #    step with the accepted/drafted counts. Verify-step cadence
    #    is decode cadence, so producers emit it ring-only (the
    #    lg_token discipline); acceptance_rate/draft_ms aggregates
    #    ride the serve_summary instead. --
    "spec_step": EventSpec(
        ("accepted",),
        optional=("drafted", "slot", "rid", "n_valid"),
    ),
    # -- paged KV cache (serve/paging.py): page lifecycle edges --
    #    alloc/free/cow/prefix_hit. Page churn runs at admission
    #    cadence, so producers emit these ring-only (flight-recorder
    #    forensics, the lg_token discipline); the aggregate hit-rate/
    #    occupancy numbers ride the serve_summary instead. --
    "kv_block": EventSpec(
        ("action",),
        optional=("rid", "slot", "n", "block", "blocks", "reason"),
    ),
    # -- host-DRAM KV page tier (serve/tier.py): one record per
    #    bounded transfer group -- parked pages leaving HBM for host
    #    buffers (kv_spill) and host-resident chains prefetched back
    #    before a returning request seats (kv_refill). Spill/refill
    #    runs at admission cadence, so producers emit these ring-only
    #    (the kv_block discipline); the wire-byte and page aggregates
    #    ride the serve_summary instead. --
    "kv_spill": EventSpec(
        ("pages", "bytes"),
        optional=("reason", "host_free", "blocks"),
    ),
    "kv_refill": EventSpec(
        ("pages", "bytes"),
        optional=("reason", "host_free", "blocks"),
    ),
    # -- resharding engine (tpu_hpc/reshard): one record per executed
    #    plan, modeled wire/peak bytes next to measured moved bytes --
    "reshard_plan": EventSpec(
        ("steps", "bytes", "wire_bytes", "peak_inflight_bytes"),
        optional=(
            "chunked_steps", "max_inflight_bytes", "bound_met",
            "kinds", "label", "measured_bytes", "predicted_cost_ms",
            "inflight_source",
        ),
    ),
    # -- collective planner (comm/planner.py): one record per resolved
    #    comm_mode="auto" decision -- the chosen strategy, predicted
    #    cost, candidate table, and whether the numbers came from a
    #    measured cost table or the alpha-beta fallback --
    "comm_plan": EventSpec(
        ("op", "mode", "source"),
        optional=(
            "payload_bytes", "dtype", "bucket_bytes",
            "predicted_cost_ms", "fingerprint", "table", "candidates",
            "reason", "resolved_from",
        ),
    ),
    # -- elastic resume (ckpt.restore_latest cross-topology path) --
    "elastic_restore": EventSpec(
        ("from_step", "src_mesh", "tgt_mesh"),
        optional=("plan", "device_count"),
    ),
    # -- live topology morph (tpu_hpc.elastic coordinator): one record
    #    per completed in-place transition -- no process exited, no
    #    checkpoint was read; the report's elastic section and the
    #    regress gate's elastic.* namespace read exactly this --
    "topology_morph": EventSpec(
        ("step", "src_mesh", "tgt_mesh", "wire_bytes", "stall_s"),
        optional=(
            "reason", "plan", "n_devices_from", "n_devices_to",
            "morph_seq", "preserved_data_extent", "compiled_programs",
            "predicted_cost_s",
        ),
    ),
    # One MPMD stage remapped onto a surviving device after its slice
    # was reclaimed (parallel/mpmd.py): the restart budget is NOT
    # charged -- the device went away, the stage did nothing wrong.
    "stage_remap": EventSpec(
        ("stage", "reason"),
        optional=("from_device", "to_device", "restore_step"),
    ),
    # -- numeric-health guard (resilience/guard.py via the Trainer):
    #    one verdict per anomalous step, one rollback record per
    #    rollback-to-last-good -- the report's guard section and the
    #    regress gate's rollback/skip counters read exactly these --
    "guard_verdict": EventSpec(
        ("step", "verdict", "action"),
        optional=(
            "grad_norm", "update_norm", "loss_finite", "nonfinite",
            "watermark", "ratio", "data_index",
            # Stage-scoped verdicts (the MPMD runtime's per-stage
            # guard path): which stage's fault domain the anomaly
            # was contained to.
            "stage",
        ),
    ),
    "guard_rollback": EventSpec(
        ("to_step", "first_bad", "last_bad", "data_from", "data_to"),
        optional=("quarantined", "n_rollbacks", "reason", "stage"),
    ),
    # -- checkpoint integrity + restore fallback (ckpt/checkpoint.py):
    #    every restore-side checksum verdict, and every fall-back-to-
    #    older (previously only a logger warning -- a silent fallback
    #    is a robustness regression the gate must see) --
    "ckpt_integrity": EventSpec(
        ("step", "verdict"), optional=("checked", "mismatched"),
    ),
    "ckpt_fallback": EventSpec(
        ("step", "error"), optional=("quarantined",),
    ),
    # -- multi-replica serving fleet (serve/fleet.py): the failure-
    #    handling contract's evidence trail. Routing runs at request
    #    cadence, so producers emit fleet_route ring-only (the
    #    lg_token discipline); the lifecycle edges below are rare and
    #    land in the sink. --
    "fleet_route": EventSpec(
        ("rid", "replica"),
        optional=("tenant", "affinity", "reason"),
    ),
    # A replica left the serving set: heartbeat timeout (killed /
    # wedged), with its in-flight count and how many requests were
    # re-dispatched onto survivors.
    "replica_down": EventSpec(
        ("replica", "reason"),
        optional=("inflight", "redispatched", "last_beat_age_s"),
    ),
    # A replica (re)joined: bring-up, jittered-backoff restart after
    # death, or autoscale activation of a warm standby.
    "replica_up": EventSpec(
        ("replica", "reason"), optional=("weights_version",),
    ),
    # One in-flight request replayed onto a survivor from prompt +
    # committed tokens (seeded/greedy determinism makes the resumed
    # stream byte-identical to the no-failure run).
    "redispatch": EventSpec(
        ("rid", "from_replica", "to_replica"),
        optional=("committed", "tenant"),
    ),
    # Autoscaler decisions over the occupancy gauge + block-stall
    # watermark: grow (standby -> live), drain_start, shrink
    # (drained -> standby).
    "fleet_scale": EventSpec(
        ("action", "live"),
        optional=("replica", "occupancy", "reason"),
    ),
    # Live weight hot-swap lifecycle per replica: drain_start ->
    # swapped, or corrupt -> rolled_back when the content checksums
    # (ckpt/integrity.py) catch a bad artifact.
    "weight_swap": EventSpec(
        ("replica", "version", "status"),
        optional=("reason", "mismatched"),
    ),
    # -- MPMD pipeline runtime (parallel/mpmd.py): the per-stage
    #    fault-domain evidence trail -- a stage leaving/rejoining the
    #    pipeline, the in-flight microbatches replayed through a
    #    recovered stage, and the per-step bubble telemetry the
    #    report's pipeline section and the regress gate's pipeline.*
    #    namespace read. --
    # A stage left the pipeline: crash (killed worker),
    # heartbeat-timeout (wedged worker), or guard-poisoned
    # (non-finite output caught before any update committed it).
    "stage_down": EventSpec(
        ("stage", "reason"),
        optional=("microbatch", "inflight", "beat_age_s"),
    ),
    # A stage rejoined after stage-local recovery: fresh worker,
    # last-good snapshot restored (checksum-verified), healthy
    # stages untouched. ``reason`` is the budget class charged:
    # restart (crash/heartbeat) or rollback (guard-poisoned).
    "stage_up": EventSpec(
        ("stage", "reason"),
        optional=("restore_step", "mttr_s", "compile_count"),
    ),
    # One in-flight microbatch the dead stage held, replayed through
    # the recovered stage (the step re-executes from its start;
    # determinism makes the replayed stream bit-identical).
    "stage_redispatch": EventSpec(("stage", "microbatch")),
    # Per-step pipeline idle fraction on the runtime's virtual
    # clock, with cross-stage slow detection's verdict riding along.
    "pipeline_bubble": EventSpec(
        ("step", "bubble_fraction"),
        optional=("makespan_s", "straggler_stage"),
    ),
    # -- live telemetry plane (obs/digest.py, obs/live.py, obs/slo.py):
    #    the fleet-wide merge layer. One health_digest per publisher
    #    period -- cumulative counters, gauge snapshot, and mergeable
    #    log-bucket histogram sketches (bounded relative error), keyed
    #    by (role, key) so the aggregator can roll N replicas, S
    #    stages, and H hosts into one fleet view. ``t`` is the
    #    publisher's clock (virtual under the harnesses -- replays are
    #    bit-identical), ``seq`` dedups re-reads of the same channel. --
    "health_digest": EventSpec(
        ("role", "key", "t", "counters", "gauges", "hists"),
        optional=("step_s", "watermark_s", "period_s", "alpha"),
    ),
    # A publisher stopped publishing: the aggregator's first-class
    # "absence of telemetry is itself a signal" record -- a wedged or
    # dead process must not silently drop out of the rollup.
    "digest_stale": EventSpec(
        ("role", "key", "age_s"),
        optional=("stale_after_s", "last_t", "last_seq"),
    ),
    # Multi-window error-budget burn (obs/slo.py): emitted once when
    # BOTH the fast and slow windows burn past the threshold -- the
    # page-worthy condition, wired to AnomalyCapture for one
    # correlated evidence bundle.
    "slo_burn": EventSpec(
        ("burn_fast", "burn_slow", "threshold", "budget"),
        optional=(
            "fast_window_s", "slow_window_s", "error_rate_fast",
            "error_rate_slow", "good", "bad", "budget_remaining",
            "reason", "t",
        ),
    ),
    # -- supervisor attempt log (resilience/supervisor.py) --
    "attempt_start": EventSpec(("attempt", "cmd")),
    "attempt_end": EventSpec(
        ("attempt", "rc", "meaning", "reason", "duration_s", "log"),
    ),
    "restarting": EventSpec(
        ("next_attempt", "backoff_s"), optional=("why",),
    ),
    "giving_up": EventSpec(("attempt", "rc", "why")),
    "heartbeat_stall": EventSpec(("attempt", "timeout_s")),
    # Morph-channel accounting (supervisor): how many live topology
    # transitions the attempt completed -- with, by contract, ZERO
    # restart/preemption/rollback budget burned (nothing exited).
    "morphs_complete": EventSpec(
        ("attempt", "count"), optional=("budget_burned",),
    ),
}


def stamp(
    record: Mapping,
    *,
    run_id: str | None = None,
    host: str | None = None,
    pid: int | None = None,
) -> dict:
    """Return a copy of ``record`` with ``schema_version``/``time`` (and
    the provenance fields, when given) filled in -- existing values are
    never overwritten, so producers that already carry a wall-clock
    ``time`` keep it."""
    rec = dict(record)
    rec.setdefault("schema_version", SCHEMA_VERSION)
    rec.setdefault("time", time.time())
    if run_id is not None:
        rec.setdefault("run_id", run_id)
    if host is not None:
        rec.setdefault("host", host)
    if pid is not None:
        rec.setdefault("pid", pid)
    return rec


def validate_record(record) -> dict:
    """Validate one record against the schema; returns it unchanged.

    Raises :class:`SchemaError` on: non-dict input, a missing/wrong
    ``schema_version``, an unknown ``event`` kind, a missing required
    field, or -- for closed kinds -- an unknown field.
    """
    if not isinstance(record, dict):
        raise SchemaError(f"record is {type(record).__name__}, not an object")
    ver = record.get("schema_version")
    if ver != SCHEMA_VERSION:
        raise SchemaError(
            f"schema_version {ver!r} != {SCHEMA_VERSION} "
            f"(event {record.get('event')!r})"
        )
    event = record.get("event")
    spec = EVENTS.get(event)
    if spec is None:
        raise SchemaError(
            f"unknown event kind {event!r} "
            f"(known: {', '.join(sorted(EVENTS))})"
        )
    missing = [
        f for f in (*COMMON_REQUIRED, *spec.required) if f not in record
    ]
    if missing:
        raise SchemaError(f"event {event!r} missing required {missing}")
    if not spec.open:
        allowed = {
            *COMMON_REQUIRED, *COMMON_OPTIONAL,
            *spec.required, *spec.optional,
        }
        unknown = sorted(set(record) - allowed)
        if unknown:
            raise SchemaError(
                f"event {event!r} carries unknown fields {unknown} "
                "(closed kind; extend EventSpec.optional or mark open)"
            )
    return record


def load_records(path: str, validate: bool = True) -> list:
    """Parse (and by default schema-validate) a JSONL file, raising
    :class:`SchemaError` naming the first bad line. The ONE
    parse-and-validate loop -- the report and the validator must not
    drift in what they accept."""
    records = []
    with open(path) as f:
        for lineno, line in enumerate(f, start=1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError as e:
                raise SchemaError(
                    f"{path}:{lineno}: not JSON ({e})"
                ) from None
            if validate:
                try:
                    validate_record(rec)
                except SchemaError as e:
                    raise SchemaError(
                        f"{path}:{lineno}: {e}"
                    ) from None
            records.append(rec)
    return records


def validate_file(path: str) -> int:
    """Validate every JSONL record in ``path``; returns the record
    count."""
    return len(load_records(path, validate=True))
