"""``python -m tpu_hpc.obs.report run.jsonl`` -- where did the time go?

Turns one run's schema-stamped JSONL (the Trainer's run log, a serve
replay's trace, or a flight-recorder dump -- they all validate against
obs/schema.py) into the report every perf or robustness change is
judged by:

* **step-time breakdown** -- per-phase seconds and shares from the
  span events (data / compute / sync / ckpt, plus any other spans
  found; phases XLA fuses away on a given path are reported as such
  instead of silently omitted);
* **goodput** -- productive vs ckpt/restore/other wall-clock, per
  attempt and combined across a preempted-and-resumed run;
* **MFU** -- when the run's config carries ``model_flops_per_item``
  and the device kind has a known peak (checks/roofline.py's spec
  table; ``--peak-flops`` overrides for sim/CPU runs);
* **restart timeline** -- one line per attempt (resumed-from step,
  end step, exit disposition);
* **serving** -- tokens/s/chip, TTFT/ITL quantiles and serving MFU
  when the file holds serve records;
* **load generator** -- per-tenant lifecycle/shed/queued breakdown
  and admission-control decisions when the file holds loadgen
  (``lg_*``) records.

``--json`` emits the same report as one JSON object for drivers.
Driver contract (pinned by tests): the JSON carries
``schema_version``; exit code 0 = report produced, 2 = empty, missing
or schema-invalid input. obs/regress.py and CI consume exactly this.
"""
from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, Optional, Sequence

from tpu_hpc.obs.schema import (  # noqa: F401
    SCHEMA_VERSION,
    SchemaError,
    load_records,
)
# (load_records re-exported: the schema module owns the one
# parse-and-validate loop; the report is just its largest consumer.)

# Canonical training phases, always shown (a phase the current path
# cannot measure separately prints a note, not a silent hole).
CANONICAL_PHASES = ("data", "compute", "sync", "ckpt")
_PHASE_NOTES = {
    "data": "on-device generator fused into the step program",
    "sync": "grad collectives fused into compute by GSPMD/XLA",
}


def _phase_breakdown(records: Sequence[dict]) -> Dict[str, dict]:
    spans = [r for r in records if r.get("event") == "span"]
    by: Dict[str, dict] = {}
    # The share denominator counts TOP-LEVEL spans only: a nested
    # span's time is already inside its parent's (that is what the
    # parent/depth fields exist for), so summing every span would
    # double-count it. Child phases still get their own rows, with
    # shares against the same wall-clock denominator.
    total = 0.0
    for s in spans:
        e = by.setdefault(s["name"], {"total_s": 0.0, "count": 0})
        e["total_s"] += float(s["dur_s"])
        e["count"] += 1
        if not s.get("depth"):
            total += float(s["dur_s"])
    for e in by.values():
        e["share"] = e["total_s"] / total if total > 0 else 0.0
    return by


def _goodput(run_ends: Sequence[dict]) -> Optional[dict]:
    if not run_ends:
        return None
    attempts = [
        {
            "attempt": r["attempt"],
            "resumed_from_step": r["resumed_from_step"],
            "step": r["step"],
            "preempted": r["preempted"],
            **r["goodput"],
        }
        for r in run_ends
    ]
    totals = {
        k: sum(a[k] for a in attempts)
        for k in ("total_s", "productive_s", "ckpt_s", "restore_s",
                  "other_s")
    }
    totals["goodput"] = (
        totals["productive_s"] / totals["total_s"]
        if totals["total_s"] > 0 else 0.0
    )
    return {"attempts": attempts, "combined": totals}


def _mfu(
    records: Sequence[dict],
    run_start: Optional[dict],
    peak_flops_per_device: Optional[float],
) -> Optional[dict]:
    if run_start is None:
        return None
    cfg = run_start.get("config") or {}
    flops_per_item = float(cfg.get("model_flops_per_item") or 0.0)
    if flops_per_item <= 0:
        return None
    peak = peak_flops_per_device
    if peak is None:
        try:
            from tpu_hpc.checks.roofline import peak_flops_for_kind

            peak = peak_flops_for_kind(
                run_start.get("device_kind", "")
            )
        except ImportError:  # pragma: no cover - minimal installs
            peak = None
    if not peak:
        return None
    # Time-weighted throughput: each epoch record's rate weighted by
    # the wall time its chunk covered (steps-advanced x s/step), so a
    # slow straggling chunk depresses the run MFU the way it
    # depressed the run. Walked in FILE ORDER with prev_step re-seeded
    # at every run_start: a preempted-and-resumed log interleaves
    # attempts, and seeding once from the last attempt would clamp
    # every earlier attempt's first chunk to a ~1-step weight.
    num = den = 0.0
    prev_step = 0
    for r in records:
        event = r.get("event")
        if event == "run_start":
            prev_step = r.get("start_step", 0)
        elif event == "epoch":
            chunk_s = max(r["step"] - prev_step, 1) * r["s_per_step"]
            prev_step = r["step"]
            num += r["items_per_s"] * chunk_s
            den += chunk_s
    if den == 0.0:
        return None
    items_per_s = num / den
    n_dev = run_start["n_devices"]
    return {
        "items_per_s": items_per_s,
        "flops_per_item": flops_per_item,
        "peak_flops_per_device": peak,
        "n_devices": n_dev,
        "mfu": items_per_s * flops_per_item / (peak * n_dev),
    }


def _serve(records: Sequence[dict]) -> Optional[dict]:
    summaries = [
        r for r in records if r.get("event") == "serve_summary"
    ]
    if not summaries:
        return None
    s = summaries[-1]
    out = {
        k: s[k]
        for k in (
            "requests", "tokens", "tokens_per_s",
            "tokens_per_s_per_chip", "ttft_ms_p50", "ttft_ms_p95",
            "ttft_ms_p99", "itl_ms_p50", "itl_ms_p95", "itl_ms_p99",
            # Paged KV cache (serve/paging.py): cache-efficiency
            # numbers next to the latency quantiles, so the regress
            # gate's serve.* namespace holds hit rate and page
            # headroom too.
            "kv_layout", "kv_block_size", "kv_blocks",
            "kv_blocks_free_min", "prefix_hit_rate", "prefix_hits",
            "prefix_hit_blocks", "prefill_chunks",
            # Speculative decoding (serve/spec.py): mode/k are
            # identity, acceptance_rate and draft_ms are the judged
            # signals (regress excludes the identity + raw counts).
            "spec_mode", "spec_k", "acceptance_rate", "draft_ms",
            "drafted", "accepted", "rejected", "verify_steps",
            # Host-DRAM KV tier (serve/tier.py): pool config
            # (kv_host_blocks, inflight) is identity, the wire bytes
            # and hop quantiles are the judged signals (regress
            # excludes the config + raw counts).
            "kv_host_blocks", "kv_host_used", "kv_host_free",
            "kv_host_drops", "kv_host_inflight_bytes",
            "kv_host_inflight_source", "kv_hop_ms_p50",
            "kv_hop_ms_p95", "kv_spills", "kv_spill_pages",
            "kv_spill_wire_bytes", "kv_refills", "kv_refill_pages",
            "kv_refill_wire_bytes",
        )
        if k in s
    }
    if "serve_mfu" in s:
        out["serve_mfu"] = s["serve_mfu"]
    stalls = (s.get("batcher") or {}).get("block_stalls")
    if stalls is not None:
        out["block_stalls"] = stalls
    return out


def _loadgen(records: Sequence[dict]) -> Optional[dict]:
    """Load-harness breakdown: per-tenant lifecycle counts and TTFT
    quantiles rebuilt from the lg_* events themselves (the breakdown
    must exist even when a run died before its serve_summary), plus
    the admission-control decision counts that attribute shed load."""
    from tpu_hpc.obs.quantiles import quantile

    headers = [
        r for r in records if r.get("event") == "load_scenario"
    ]
    lifecycle = [
        r for r in records
        if r.get("event") in (
            "lg_arrival", "lg_admit", "lg_first_token", "lg_finish",
            "lg_shed",
        )
    ]
    admissions = [
        r for r in records if r.get("event") == "admission"
    ]
    if not headers and not lifecycle and not admissions:
        return None
    tenants: Dict[str, dict] = {}

    def entry(name: str) -> dict:
        return tenants.setdefault(name, {
            "arrivals": 0, "admitted": 0, "queued": 0,
            "finished": 0, "shed": 0, "_ttfts": [],
        })

    for r in lifecycle:
        e = entry(r["tenant"])
        ev = r["event"]
        if ev == "lg_arrival":
            e["arrivals"] += 1
        elif ev == "lg_admit":
            e["admitted"] += 1
            # The producer's explicit tick-aware flag when present;
            # queue_ms alone over-counts same-tick admissions (an
            # earlier slot's prefill advances the shared clock).
            if r.get("queued", r["queue_ms"] > 1e-9):
                e["queued"] += 1
        elif ev == "lg_first_token":
            e["_ttfts"].append(float(r["ttft_ms"]))
        elif ev == "lg_finish":
            e["finished"] += 1
        elif ev == "lg_shed":
            e["shed"] += 1
    summaries = [
        r for r in records
        if r.get("event") == "serve_summary" and "scenario" in r
    ]
    if summaries:
        # Per-tenant ITL quantiles exist only in the closing
        # summary: lg_token is ring-only by design, so the file's
        # lifecycle events cannot reconstruct them. Merge them in so
        # the regress gate sees per-tenant ITL too.
        for name, st in (summaries[-1].get("tenants") or {}).items():
            e = entry(name)
            for k in ("itl_ms_p50", "itl_ms_p95"):
                if k in st:
                    e[k] = st[k]
    for e in tenants.values():
        ttfts = sorted(e.pop("_ttfts"))
        e["ttft_ms_p50"] = quantile(ttfts, 0.50)
        e["ttft_ms_p95"] = quantile(ttfts, 0.95)
        e["ttft_ms_p99"] = quantile(ttfts, 0.99)
    decisions = {"shed": 0, "queue": 0}
    for r in admissions:
        decisions[r["action"]] = decisions.get(r["action"], 0) + 1
    # The closing serve_summary's loadgen extras (occupancy, SLO
    # verdicts) ride along when present.
    out: dict = {"tenants": tenants, "admission_decisions": decisions}
    if headers:
        out["scenario"] = headers[-1]["scenario"]
        out["seed"] = headers[-1]["seed"]
    if summaries:
        s = summaries[-1]
        for k in ("occupancy_mean", "occupancy_p95", "stall_events",
                  "slo_violations", "shed", "queued"):
            if k in s:
                out[k] = s[k]
    return out


def _fleet(records: Sequence[dict]) -> Optional[dict]:
    """Serving-fleet breakdown (serve/fleet.py): replica losses,
    redispatched requests, weight-swap outcomes and autoscale
    decisions from the fleet lifecycle events, plus the router's
    prefix-affinity outcome from the closing summary -- the
    robustness counters the regress gate's ``fleet.*`` namespace
    judges."""
    downs = [r for r in records if r.get("event") == "replica_down"]
    ups = [r for r in records if r.get("event") == "replica_up"]
    redispatches = [
        r for r in records if r.get("event") == "redispatch"
    ]
    swaps = [r for r in records if r.get("event") == "weight_swap"]
    scales = [r for r in records if r.get("event") == "fleet_scale"]
    summaries = [
        r for r in records
        if r.get("event") == "serve_summary" and "fleet" in r
    ]
    if not (downs or ups or redispatches or swaps or scales
            or summaries):
        return None
    out = {
        "replica_down": len(downs),
        "redispatched": len(redispatches),
        "restarts": sum(
            1 for r in ups if r["reason"] == "restart"
        ),
        "swapped_replicas": sum(
            1 for r in swaps if r["status"] == "swapped"
        ),
        "swap_rollbacks": sum(
            1 for r in swaps if r["status"] == "rolled_back"
        ),
        "scale_ups": sum(
            1 for r in scales if r["action"] == "grow"
        ),
        "scale_downs": sum(
            1 for r in scales if r["action"] == "shrink"
        ),
    }
    if summaries:
        f = summaries[-1]["fleet"]
        for k in ("replicas", "live_min", "live_max",
                  "prefix_affinity_hit_rate", "router",
                  "affinity_routes", "weights_version",
                  "mixed_weights"):
            if k in f:
                out[k] = f[k]
    return out


def _pipeline(records: Sequence[dict]) -> Optional[dict]:
    """MPMD pipeline breakdown (parallel/mpmd.py): per-stage
    up/down timeline, in-flight replays, bubble fraction and
    recovery MTTR -- the robustness counters the regress gate's
    ``pipeline.*`` namespace judges."""
    downs = [r for r in records if r.get("event") == "stage_down"]
    ups = [r for r in records if r.get("event") == "stage_up"]
    redispatches = [
        r for r in records if r.get("event") == "stage_redispatch"
    ]
    bubbles = [
        r for r in records if r.get("event") == "pipeline_bubble"
    ]
    if not (downs or ups or redispatches or bubbles):
        return None
    timeline: Dict[str, list] = {}
    for r in (*downs, *ups):
        entry = {
            "t": r.get("time"),
            "event": "down" if r["event"] == "stage_down" else "up",
            "reason": r["reason"],
        }
        if r["event"] == "stage_down" and "step" in r:
            entry["step"] = r["step"]
        timeline.setdefault(str(r["stage"]), []).append(entry)
    for entries in timeline.values():
        entries.sort(key=lambda e: (e["t"] is None, e["t"]))
    mttrs = [r["mttr_s"] for r in ups if "mttr_s" in r]
    stragglers = sorted({
        r["straggler_stage"] for r in bubbles
        if r.get("straggler_stage") is not None
    })
    return {
        "stage_down": len(downs),
        "redispatched": len(redispatches),
        "restarts": sum(1 for r in ups if r["reason"] == "restart"),
        "rollbacks": sum(
            1 for r in ups if r["reason"] == "rollback"
        ),
        "bubble_fraction": (
            sum(r["bubble_fraction"] for r in bubbles) / len(bubbles)
            if bubbles else None
        ),
        "recovery_mttr_s": (
            sum(mttrs) / len(mttrs) if mttrs else None
        ),
        "straggler_stages": stragglers,
        "stages": timeline,
    }


def _live(records: Sequence[dict]) -> Optional[dict]:
    """Live telemetry plane breakdown (obs/digest, obs/live, obs/slo):
    the fleet-rollup verdicts -- per-role straggler/stale flags, SLO
    attainment and error-budget remaining, burn-rate pages -- from
    the ``health_digest``/``digest_stale``/``slo_burn`` records in
    the log plus the closing summary's ``live`` block. The regress
    gate's ``live.*``/``slo.*`` namespaces judge exactly these."""
    digests = [
        r for r in records if r.get("event") == "health_digest"
    ]
    stales = [r for r in records if r.get("event") == "digest_stale"]
    burns = [r for r in records if r.get("event") == "slo_burn"]
    summaries = [
        r for r in records
        if r.get("event") == "serve_summary" and "live" in r
    ]
    if not (digests or stales or burns or summaries):
        return None
    out: dict = {
        "digests": len(digests),
        "digest_stale": len(stales),
        "stale_keys": sorted({
            f"{r['role']}:{r['key']}" for r in stales
        }),
        "slo_burns": len(burns),
        "stragglers": [],
    }
    if digests:
        # Re-derive the per-role rollup from the digests the log
        # holds -- same merge the live aggregator runs, so the
        # post-hoc report and the live scoreboard cannot disagree.
        from tpu_hpc.obs.live import Rollup

        view = Rollup().ingest(digests).build()
        out["roles"] = {
            role: {
                "keys": sorted(block["keys"]),
                "stragglers": block["stragglers"],
                "stale": block["stale"],
                "counters": block["counters"],
            }
            for role, block in view["roles"].items()
        }
        out["stragglers"] = view["stragglers"]
    if burns:
        b = burns[-1]
        out["burn_fast"] = b["burn_fast"]
        out["burn_slow"] = b["burn_slow"]
        out["burn_trace_id"] = b.get("trace_id")
        if b.get("budget_remaining") is not None:
            out["budget_remaining"] = b["budget_remaining"]
    if summaries:
        lv = summaries[-1]["live"]
        for k in ("stragglers", "slo_attainment", "budget_remaining",
                  "slo_good", "slo_bad", "digests"):
            if lv.get(k) is not None:
                out[k] = lv[k]
        out["digest_stale"] = max(
            out["digest_stale"], lv.get("digest_stale", 0) or 0
        )
    return out


def _elastic(records: Sequence[dict]) -> Optional[dict]:
    """Topology-morph breakdown (tpu_hpc.elastic): the per-morph
    timeline plus the totals the regress gate's ``elastic.*``
    namespace judges -- morph count, wire bytes moved, quiesce-to-
    resume stall. MPMD stage-slice remaps (budget-free recoveries)
    ride along."""
    morphs = [
        r for r in records if r.get("event") == "topology_morph"
    ]
    remaps = [r for r in records if r.get("event") == "stage_remap"]
    if not morphs and not remaps:
        return None
    return {
        "morphs": len(morphs),
        "wire_bytes": sum(
            int(r.get("wire_bytes", 0)) for r in morphs
        ),
        "stall_s": round(
            sum(float(r.get("stall_s", 0.0)) for r in morphs), 6
        ),
        "stage_remaps": len(remaps),
        "timeline": [
            {
                "step": r["step"],
                "reason": r.get("reason"),
                "src_mesh": r["src_mesh"],
                "tgt_mesh": r["tgt_mesh"],
                "wire_bytes": r["wire_bytes"],
                "stall_s": r["stall_s"],
                "preserved_data_extent": r.get(
                    "preserved_data_extent"
                ),
            }
            for r in morphs
        ],
    }


def _guard(records: Sequence[dict]) -> Optional[dict]:
    """Numeric-health guard breakdown: verdict counts, skip count,
    and the rollback timeline with its goodput cost (steps re-trained
    plus poisoned batches skipped -- the price of each anomaly)."""
    verdicts = [
        r for r in records if r.get("event") == "guard_verdict"
    ]
    rollbacks = [
        r for r in records if r.get("event") == "guard_rollback"
    ]
    if not verdicts and not rollbacks:
        return None
    out = {
        "poisoned": sum(
            1 for v in verdicts if v["verdict"] == "poisoned"
        ),
        "spikes": sum(1 for v in verdicts if v["verdict"] == "spike"),
        "skipped": sum(
            1 for v in verdicts if v.get("action") == "skip"
        ),
        "rollbacks": [
            {
                "to_step": r["to_step"],
                "first_bad": r["first_bad"],
                "last_bad": r["last_bad"],
                "data_from": r["data_from"],
                "data_to": r["data_to"],
                "quarantined": r.get("quarantined") or [],
            }
            for r in rollbacks
        ],
        # Poisoned-window goodput loss, in optimizer steps: each
        # rollback re-trains [to_step, first_bad) and skips the
        # anomaly window itself -- all work the anomaly destroyed.
        "lost_steps": sum(
            r["last_bad"] + 1 - r["to_step"] for r in rollbacks
        ),
    }
    return out


def _memory(records: Sequence[dict]) -> Optional[dict]:
    """HBM high-water marks from ``device_memory`` events
    (profiling/profiler.device_memory_summary, also emitted by every
    anomaly capture): the max across records is the run's peak."""
    mems = [
        r for r in records if r.get("event") == "device_memory"
    ]
    if not mems:
        return None
    return {
        "snapshots": len(mems),
        "hbm_peak_bytes": max(r["hbm_peak_bytes"] for r in mems),
        "hbm_limit_bytes": max(
            (r["hbm_limit_bytes"] for r in mems
             if "hbm_limit_bytes" in r),
            default=None,
        ),
    }


def _ckpt(records: Sequence[dict]) -> Optional[dict]:
    """Checkpoint-health breakdown: restore fallbacks (each one a
    snapshot that silently failed to come back) and content-integrity
    verdicts."""
    fallbacks = [
        r for r in records if r.get("event") == "ckpt_fallback"
    ]
    integrity = [
        r for r in records if r.get("event") == "ckpt_integrity"
    ]
    if not fallbacks and not integrity:
        return None
    return {
        "fallbacks": len(fallbacks),
        "fallback_steps": [r["step"] for r in fallbacks],
        "quarantined": [
            r["quarantined"] for r in fallbacks if r.get("quarantined")
        ],
        "integrity_checks": len(integrity),
        "integrity_failures": sum(
            1 for r in integrity if r["verdict"] != "ok"
        ),
    }


def build_report(
    records: Sequence[dict],
    peak_flops_per_device: Optional[float] = None,
) -> dict:
    """Aggregate a record list into the report dict (the ``--json``
    output; ``format_report`` renders it for humans)."""
    run_starts = [r for r in records if r.get("event") == "run_start"]
    run_ends = [r for r in records if r.get("event") == "run_end"]
    stalls = [r for r in records if r.get("event") == "stall"]
    faults = [r for r in records if r.get("event") == "fault"]
    run_start = run_starts[-1] if run_starts else None
    return {
        # The --json contract: drivers (obs/regress.py, CI) key on
        # this stamp the same way record consumers do.
        "schema_version": SCHEMA_VERSION,
        "run_id": next(
            (r["run_id"] for r in records if "run_id" in r), None
        ),
        "n_records": len(records),
        "phases": _phase_breakdown(records),
        "goodput": _goodput(run_ends),
        "mfu": _mfu(records, run_start, peak_flops_per_device),
        "timeline": [
            {
                "attempt": r["attempt"],
                "resumed_from_step": r["resumed_from_step"],
                "end_step": r["step"],
                "disposition": (
                    "preempted (resumable snapshot)" if r["preempted"]
                    else "completed"
                ),
            }
            for r in run_ends
        ],
        "stalls": len(stalls),
        "faults": [
            {"kind": f["kind"], "step": f.get("step")} for f in faults
        ],
        "serve": _serve(records),
        "loadgen": _loadgen(records),
        "fleet": _fleet(records),
        "pipeline": _pipeline(records),
        "elastic": _elastic(records),
        "live": _live(records),
        "guard": _guard(records),
        "ckpt": _ckpt(records),
        "memory": _memory(records),
    }


def format_report(rep: dict) -> str:
    lines = [
        f"# tpu_hpc run report -- run_id {rep['run_id'] or '(none)'} "
        f"({rep['n_records']} records)",
        "",
        "## Step-time breakdown (span events)",
        "",
        "| phase | total_s | share | spans |",
        "|---|---|---|---|",
    ]
    phases = rep["phases"]
    shown = set()
    for name in (*CANONICAL_PHASES, *sorted(phases)):
        if name in shown:
            continue
        shown.add(name)
        e = phases.get(name)
        if e is not None:
            lines.append(
                f"| {name} | {e['total_s']:.3f} | {e['share']:.1%} "
                f"| {e['count']} |"
            )
        else:
            note = _PHASE_NOTES.get(name, "not measured on this run")
            lines.append(f"| {name} | - | - | {note} |")
    lines.append("")
    gp = rep["goodput"]
    lines.append("## Goodput")
    lines.append("")
    if gp is None:
        lines.append("no run_end record (run died before closing, or "
                     "not a training log)")
    else:
        for a in gp["attempts"]:
            lines.append(
                f"- attempt {a['attempt']}: steps "
                f"{a['resumed_from_step']} -> {a['step']}, productive "
                f"{a['productive_s']:.2f}s / total {a['total_s']:.2f}s "
                f"= {a['goodput']:.1%} (ckpt {a['ckpt_s']:.2f}s, "
                f"restore {a['restore_s']:.2f}s, other "
                f"{a['other_s']:.2f}s)"
            )
        c = gp["combined"]
        lines.append(
            f"- **combined**: productive {c['productive_s']:.2f}s / "
            f"total {c['total_s']:.2f}s = **{c['goodput']:.1%} "
            "goodput**"
        )
    lines.append("")
    lines.append("## MFU")
    lines.append("")
    m = rep["mfu"]
    if m is None:
        lines.append(
            "unavailable: needs config.model_flops_per_item in the "
            "run_start record and a known device peak (or "
            "--peak-flops)"
        )
    else:
        lines.append(
            f"{m['mfu']:.1%} -- {m['items_per_s']:.1f} items/s x "
            f"{m['flops_per_item']:.3g} FLOPs/item over "
            f"{m['n_devices']} device(s) at "
            f"{m['peak_flops_per_device']:.3g} peak FLOP/s each"
        )
    lines.append("")
    lines.append("## Restart timeline")
    lines.append("")
    if not rep["timeline"]:
        lines.append("(no attempts recorded)")
    for t in rep["timeline"]:
        lines.append(
            f"- attempt {t['attempt']}: resumed from step "
            f"{t['resumed_from_step']}, ended at step {t['end_step']} "
            f"-- {t['disposition']}"
        )
    if rep["stalls"]:
        lines.append(f"- stall events flagged: {rep['stalls']}")
    for f in rep["faults"]:
        lines.append(
            f"- injected fault: {f['kind']} at step {f['step']}"
        )
    g = rep.get("guard")
    if g is not None:
        lines += [
            "",
            "## Numeric-health guard",
            "",
            f"- verdicts: {g['poisoned']} poisoned, {g['spikes']} "
            f"spike(s); {g['skipped']} update(s) skipped on-device",
        ]
        for r in g["rollbacks"]:
            lines.append(
                f"- ROLLBACK: anomaly steps [{r['first_bad']}, "
                f"{r['last_bad']}] -> resumed from last-good step "
                f"{r['to_step']}, data indices [{r['data_from']}, "
                f"{r['data_to']}] skipped"
                + (
                    f", quarantined snapshots {r['quarantined']}"
                    if r["quarantined"] else ""
                )
            )
        if g["rollbacks"]:
            lines.append(
                f"- poisoned-window goodput loss: {g['lost_steps']} "
                "optimizer step(s) re-trained or skipped"
            )
    mem = rep.get("memory")
    if mem is not None:
        lines += [
            "",
            "## Device memory",
            "",
            f"- HBM peak {mem['hbm_peak_bytes'] / 2**30:.2f} GiB "
            + (
                f"of {mem['hbm_limit_bytes'] / 2**30:.2f} GiB limit "
                if mem.get("hbm_limit_bytes") else ""
            )
            + f"({mem['snapshots']} snapshot(s))",
        ]
    ck = rep.get("ckpt")
    if ck is not None:
        lines += [
            "",
            "## Checkpoint health",
            "",
            f"- restore fallbacks: {ck['fallbacks']} "
            f"(steps {ck['fallback_steps']})",
            f"- integrity: {ck['integrity_failures']} failure(s) in "
            f"{ck['integrity_checks']} verified restore(s)",
        ]
        if ck["quarantined"]:
            lines.append(
                f"- quarantined: {', '.join(ck['quarantined'])}"
            )
    if rep["serve"] is not None:
        s = rep["serve"]
        lines += [
            "",
            "## Serving",
            "",
            f"- {s.get('tokens_per_s', 0):.1f} tokens/s "
            f"({s.get('tokens_per_s_per_chip', 0):.1f}/chip), "
            f"{s.get('requests')} requests",
            f"- TTFT p50/p95: {s.get('ttft_ms_p50', 0):.1f} / "
            f"{s.get('ttft_ms_p95', 0):.1f} ms; ITL p50/p95: "
            f"{s.get('itl_ms_p50', 0):.1f} / "
            f"{s.get('itl_ms_p95', 0):.1f} ms",
        ]
        if "serve_mfu" in s:
            lines.append(f"- serving MFU (2N forward accounting): "
                         f"{s['serve_mfu']:.1%}")
        if s.get("kv_layout") == "paged":
            blocks = s.get("kv_blocks", 0)
            free_min = s.get("kv_blocks_free_min", 0)
            occ_peak = (
                1.0 - free_min / max(1, blocks - 1)
            )
            lines.append(
                f"- paged KV cache: {blocks} pages x "
                f"{s.get('kv_block_size', 0)} tokens, peak occupancy "
                f"{occ_peak:.0%} (min {free_min} pages free); prefix "
                f"cache hit rate {s.get('prefix_hit_rate', 0.0):.0%} "
                f"({s.get('prefix_hit_blocks', 0)} pages reused, "
                f"{s.get('prefill_chunks', 0)} prefill chunks)"
            )
        if s.get("kv_host_blocks"):
            lines.append(
                f"- host KV tier: {s['kv_host_blocks']} host slots "
                f"({s.get('kv_host_used', 0)} used, "
                f"{s.get('kv_host_drops', 0)} drops); "
                f"{s.get('kv_spill_pages', 0)} pages spilled / "
                f"{s.get('kv_refill_pages', 0)} refilled "
                f"({s.get('kv_spill_wire_bytes', 0)} + "
                f"{s.get('kv_refill_wire_bytes', 0)} wire bytes), "
                f"hop p50/p95 {s.get('kv_hop_ms_p50', 0.0):.1f} / "
                f"{s.get('kv_hop_ms_p95', 0.0):.1f} ms "
                f"(inflight bound "
                f"{s.get('kv_host_inflight_bytes', 0)} B, "
                f"{s.get('kv_host_inflight_source', '?')})"
            )
        if s.get("spec_mode"):
            lines.append(
                f"- speculative decode ({s['spec_mode']}, "
                f"k={s.get('spec_k')}): acceptance "
                f"{s.get('acceptance_rate', 0.0):.0%} "
                f"({s.get('accepted', 0)}/{s.get('drafted', 0)} "
                f"drafts over {s.get('verify_steps', 0)} verify "
                f"steps), draft cost {s.get('draft_ms', 0.0):.1f} ms"
            )
    lg = rep.get("loadgen")
    if lg is not None:
        lines += [
            "",
            "## Load generator",
            "",
        ]
        if "scenario" in lg:
            lines.append(
                f"scenario `{lg['scenario']}` seed {lg['seed']}"
            )
            lines.append("")
        lines += [
            "| tenant | arrivals | admitted | queued | shed | "
            "finished | TTFT p50/p95/p99 (ms) |",
            "|---|---|---|---|---|---|---|",
        ]
        for name in sorted(lg["tenants"]):
            t = lg["tenants"][name]
            lines.append(
                f"| {name} | {t['arrivals']} | {t['admitted']} | "
                f"{t['queued']} | {t['shed']} | {t['finished']} | "
                f"{t['ttft_ms_p50']:.1f} / {t['ttft_ms_p95']:.1f} / "
                f"{t['ttft_ms_p99']:.1f} |"
            )
        dec = lg["admission_decisions"]
        lines.append("")
        lines.append(
            f"- admission decisions: {dec.get('shed', 0)} shed, "
            f"{dec.get('queue', 0)} saturated-queue ticks"
        )
        if "occupancy_mean" in lg:
            lines.append(
                f"- occupancy mean {lg['occupancy_mean']:.1%} / "
                f"p95 {lg.get('occupancy_p95', 0):.1%}; stall events "
                f"{lg.get('stall_events', 0)}"
            )
        if lg.get("slo_violations"):
            lines.append(
                "- SLO VIOLATED: " + ", ".join(lg["slo_violations"])
            )
    pl = rep.get("pipeline")
    if pl is not None:
        bub = pl.get("bubble_fraction")
        mttr = pl.get("recovery_mttr_s")
        lines += [
            "",
            "## MPMD pipeline",
            "",
            f"- stage failures: {pl['stage_down']} down "
            f"({pl['restarts']} restart(s), {pl['rollbacks']} "
            f"rollback(s)); {pl['redispatched']} in-flight "
            "microbatch(es) replayed",
            "- bubble fraction "
            + (f"{bub:.1%}" if bub is not None else "(not measured)")
            + "; recovery MTTR "
            + (f"{mttr:.2f}s" if mttr is not None else "n/a"),
        ]
        if pl["straggler_stages"]:
            lines.append(
                "- straggler stage(s) flagged: "
                + ", ".join(str(s) for s in pl["straggler_stages"])
            )
        for sid in sorted(pl["stages"], key=int):
            steps = " -> ".join(
                f"{e['event']}[{e['reason']}]"
                + (f"@step{e['step']}" if "step" in e else "")
                for e in pl["stages"][sid]
            )
            lines.append(f"- stage {sid} timeline: {steps}")
    el = rep.get("elastic")
    if el is not None:
        lines += [
            "",
            "## Topology morphs",
            "",
            f"- {el['morphs']} live transition(s), "
            f"{el['wire_bytes'] / 2**20:.2f} MiB over the wire, "
            f"{el['stall_s']:.3f}s total stall -- zero process "
            "restarts",
        ]
        for m in el["timeline"]:
            lines.append(
                f"- step {m['step']}: {m['src_mesh']} -> "
                f"{m['tgt_mesh']} ({m['reason']}), "
                f"{m['wire_bytes']} wire bytes in "
                f"{m['stall_s']:.3f}s"
                + (
                    "" if m.get("preserved_data_extent")
                    else " [data extent changed -- bit-exact "
                    "continuity given up]"
                )
            )
        if el["stage_remaps"]:
            lines.append(
                "- MPMD stage remaps (restart budget not burned): "
                f"{el['stage_remaps']}"
            )
    fl = rep.get("fleet")
    if fl is not None:
        lines += [
            "",
            "## Serving fleet",
            "",
            f"- replicas: {fl.get('replicas', '?')} "
            f"(live {fl.get('live_min', '?')}..{fl.get('live_max', '?')}); "
            f"router {fl.get('router', '?')}, prefix-affinity hit "
            f"rate {fl.get('prefix_affinity_hit_rate', 0.0):.0%}",
            f"- failures: {fl['replica_down']} replica(s) down, "
            f"{fl['redispatched']} request(s) redispatched, "
            f"{fl['restarts']} restart(s)",
            f"- weight swaps: {fl['swapped_replicas']} swapped, "
            f"{fl['swap_rollbacks']} rolled back (checksum)",
            f"- autoscale: {fl['scale_ups']} grow, "
            f"{fl['scale_downs']} shrink",
        ]
    lv = rep.get("live")
    if lv is not None:
        lines += [
            "",
            "## Fleet rollup (live telemetry plane)",
            "",
            f"- {lv['digests']} health digest(s) merged; "
            f"{lv['digest_stale']} publisher(s) went stale"
            + (
                f" ({', '.join(lv['stale_keys'])})"
                if lv.get("stale_keys") else ""
            ),
        ]
        if lv.get("roles"):
            lines += [
                "",
                "| role | keys | stragglers | stale |",
                "|---|---|---|---|",
            ]
            for role, block in sorted(lv["roles"].items()):
                lines.append(
                    f"| {role} | {len(block['keys'])} "
                    f"| {', '.join(block['stragglers']) or '-'} "
                    f"| {', '.join(block['stale']) or '-'} |"
                )
            lines.append("")
        if lv.get("stragglers"):
            lines.append(
                f"- stragglers vs peer median: "
                f"{', '.join(lv['stragglers'])}"
            )
        if lv.get("slo_attainment") is not None:
            budget = lv.get("budget_remaining")
            lines.append(
                f"- SLO attainment {lv['slo_attainment']:.4f}"
                + (
                    f"; error budget remaining {budget:.1%}"
                    if budget is not None else ""
                )
            )
        if lv["slo_burns"]:
            lines.append(
                f"- {lv['slo_burns']} burn-rate page(s): fast burn "
                f"{lv.get('burn_fast', '?')}x, slow burn "
                f"{lv.get('burn_slow', '?')}x"
                + (
                    f" (trace {lv['burn_trace_id']})"
                    if lv.get("burn_trace_id") else ""
                )
            )
        else:
            lines.append("- no burn-rate pages")
    return "\n".join(lines) + "\n"


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="tpu_hpc.obs.report",
        description=__doc__.split("\n")[0],
    )
    ap.add_argument("path", help="run JSONL (metrics log, serve "
                    "trace, or flight-recorder dump)")
    ap.add_argument("--json", action="store_true",
                    help="emit the report as one JSON object")
    ap.add_argument(
        "--peak-flops", type=float, default=None,
        help="peak FLOP/s per device for MFU (overrides the "
        "device-kind spec table; required on CPU-sim runs)",
    )
    ap.add_argument(
        "--no-validate", action="store_true",
        help="skip schema validation (salvage partially-corrupt logs)",
    )
    args = ap.parse_args(argv)
    try:
        records = load_records(args.path, validate=not args.no_validate)
    except OSError as e:
        print(f"tpu_hpc.obs.report: {e}", file=sys.stderr)
        return 2
    except SchemaError as e:
        print(f"tpu_hpc.obs.report: schema error: {e}", file=sys.stderr)
        return 2
    if not records:
        print(
            f"tpu_hpc.obs.report: {args.path} holds no records",
            file=sys.stderr,
        )
        return 2
    rep = build_report(records, peak_flops_per_device=args.peak_flops)
    if args.json:
        print(json.dumps(rep))
    else:
        print(format_report(rep), end="")
    return 0


if __name__ == "__main__":
    sys.exit(main())
