"""tpu_hpc.obs -- the unified telemetry spine.

Every subsystem (train, serve, resilience, bench) emits into ONE
schema-stamped JSONL discipline:

  schema.py    the record schema: required/optional fields per event
               kind, ``schema_version`` on every record, a validator.
  events.py    the structured event bus: JSONL sink + bounded in-memory
               flight-recorder ring dumped on SIGTERM / watchdog fire /
               injected fault.
  spans.py     nestable span timers (also emit
               jax.profiler.TraceAnnotation, so XProf and the JSONL
               agree on where time went).
  registry.py  counters / gauges / histograms with JSONL snapshots and
               Prometheus text exposition.
  stall.py     rolling step-time watermark detector (straggler / stall
               flagging; feeds the heartbeat file).
  quantiles.py the one quantile estimator (numpy-parity linear
               interpolation) every latency number comes from.
  trace.py     end-to-end causal tracing: run_id-scoped trace
               contexts stamped into every span/event, the
               ``python -m tpu_hpc.obs.trace`` critical-path analyzer
               (TTFT/step decomposition + Chrome-trace export), and
               anomaly-triggered capture (stall/guard/SLO trip ->
               bounded profiler trace + flight dump, keyed by
               trace_id).
  digest.py    mergeable per-process health digests: cumulative
               counters + gauges + log-bucket histogram sketches
               (bounded relative error, associative merge), appended
               to per-process channels under $TPU_HPC_DIGEST_DIR.
  live.py      fleet rollup aggregator over the digest channels:
               straggler/stale verdicts, ``python -m tpu_hpc.obs.live``
               --json driver contract / --watch scoreboard, and the
               fleet-merged Prometheus textfile.
  slo.py       multi-window error-budget burn-rate monitor (fast AND
               slow window must both burn to page) over the rollup's
               fleet SLO totals; pages once, arms AnomalyCapture.
  report.py    ``python -m tpu_hpc.obs.report run.jsonl`` -- goodput /
               MFU / step-time-breakdown report from a run's JSONL.
  regress.py   ``python -m tpu_hpc.obs.regress base.jsonl cand.jsonl``
               -- the SLO-driven perf-regression gate over report
               quantiles (and, with --bank, the bench history).
  bank.py      ``python -m tpu_hpc.obs.bank BENCH_r*.json`` --
               normalize driver bench captures into one validated
               history JSONL for regress --bank.
"""
from tpu_hpc.obs.events import (  # noqa: F401
    ENV_EVENTS,
    ENV_FLIGHT_DIR,
    ENV_RUN_ID,
    EventBus,
    dump_flight,
    get_bus,
    set_bus,
)
from tpu_hpc.obs.digest import (  # noqa: F401
    ENV_DIGEST_DIR,
    DigestPublisher,
    LogBucketSketch,
    read_digest_dir,
)
from tpu_hpc.obs.live import (  # noqa: F401
    ENV_FLEET_PROM_FILE,
    Rollup,
    format_scoreboard,
    rollup_from_dir,
    stale_entries,
    write_fleet_prometheus,
)
from tpu_hpc.obs.quantiles import quantile, summarize  # noqa: F401
from tpu_hpc.obs.registry import (  # noqa: F401
    ENV_PROM_FILE,
    MetricsRegistry,
    get_registry,
    set_registry,
)
from tpu_hpc.obs.schema import (  # noqa: F401
    SCHEMA_VERSION,
    SchemaError,
    stamp,
    validate_file,
    validate_record,
)
from tpu_hpc.obs.slo import BurnRateMonitor  # noqa: F401
from tpu_hpc.obs.spans import emit_span, span  # noqa: F401
from tpu_hpc.obs.stall import StallDetector  # noqa: F401

# trace.py exports are lazy (PEP 562): eagerly importing the module
# here would make ``python -m tpu_hpc.obs.trace`` re-execute it under
# runpy with a sys.modules warning. ``from tpu_hpc.obs import
# activate`` etc. still work -- module __getattr__ covers from-imports.
_TRACE_EXPORTS = (
    "AnomalyCapture",
    "TraceContext",
    "activate",
    "request_trace_id",
    "step_trace_id",
    "trace_id_for",
)


def __getattr__(name):
    if name in _TRACE_EXPORTS:
        from tpu_hpc.obs import trace

        return getattr(trace, name)
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}"
    )


__all__ = [
    "AnomalyCapture",
    "BurnRateMonitor",
    "DigestPublisher",
    "ENV_DIGEST_DIR",
    "ENV_EVENTS",
    "ENV_FLEET_PROM_FILE",
    "ENV_FLIGHT_DIR",
    "ENV_PROM_FILE",
    "ENV_RUN_ID",
    "EventBus",
    "LogBucketSketch",
    "MetricsRegistry",
    "Rollup",
    "SCHEMA_VERSION",
    "SchemaError",
    "StallDetector",
    "TraceContext",
    "activate",
    "dump_flight",
    "emit_span",
    "format_scoreboard",
    "get_bus",
    "get_registry",
    "quantile",
    "read_digest_dir",
    "request_trace_id",
    "rollup_from_dir",
    "set_bus",
    "set_registry",
    "span",
    "stale_entries",
    "stamp",
    "step_trace_id",
    "summarize",
    "trace_id_for",
    "validate_file",
    "validate_record",
]
