"""tpu_hpc.obs -- the unified telemetry spine.

Every subsystem (train, serve, resilience, bench) emits into ONE
schema-stamped JSONL discipline:

  schema.py    the record schema: required/optional fields per event
               kind, ``schema_version`` on every record, a validator.
  events.py    the structured event bus: JSONL sink + bounded in-memory
               flight-recorder ring dumped on SIGTERM / watchdog fire /
               injected fault.
  spans.py     nestable span timers (also emit
               jax.profiler.TraceAnnotation, so XProf and the JSONL
               agree on where time went).
  registry.py  counters / gauges / histograms with JSONL snapshots and
               Prometheus text exposition.
  stall.py     rolling step-time watermark detector (straggler / stall
               flagging; feeds the heartbeat file).
  quantiles.py the one quantile estimator (numpy-parity linear
               interpolation) every latency number comes from.
  report.py    ``python -m tpu_hpc.obs.report run.jsonl`` -- goodput /
               MFU / step-time-breakdown report from a run's JSONL.
  regress.py   ``python -m tpu_hpc.obs.regress base.jsonl cand.jsonl``
               -- the SLO-driven perf-regression gate over report
               quantiles (and, with --bank, the bench history).
  bank.py      ``python -m tpu_hpc.obs.bank BENCH_r*.json`` --
               normalize driver bench captures into one validated
               history JSONL for regress --bank.
"""
from tpu_hpc.obs.events import (  # noqa: F401
    ENV_EVENTS,
    ENV_FLIGHT_DIR,
    ENV_RUN_ID,
    EventBus,
    dump_flight,
    get_bus,
    set_bus,
)
from tpu_hpc.obs.quantiles import quantile, summarize  # noqa: F401
from tpu_hpc.obs.registry import (  # noqa: F401
    ENV_PROM_FILE,
    MetricsRegistry,
    get_registry,
    set_registry,
)
from tpu_hpc.obs.schema import (  # noqa: F401
    SCHEMA_VERSION,
    SchemaError,
    stamp,
    validate_file,
    validate_record,
)
from tpu_hpc.obs.spans import emit_span, span  # noqa: F401
from tpu_hpc.obs.stall import StallDetector  # noqa: F401

__all__ = [
    "ENV_EVENTS",
    "ENV_FLIGHT_DIR",
    "ENV_PROM_FILE",
    "ENV_RUN_ID",
    "EventBus",
    "MetricsRegistry",
    "SCHEMA_VERSION",
    "SchemaError",
    "StallDetector",
    "dump_flight",
    "emit_span",
    "get_bus",
    "get_registry",
    "quantile",
    "set_bus",
    "set_registry",
    "span",
    "stamp",
    "summarize",
    "validate_file",
    "validate_record",
]
