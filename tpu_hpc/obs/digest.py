"""Mergeable per-process health digests: the fleet's gossip unit.

Everything in obs/ so far is per-process and mostly post-hoc: the
registry's Prometheus textfile covers one process, ``obs.report``
reads a finished JSONL. A fleet of N serving replicas, S MPMD stages
and H training hosts needs the *live* union, and the fleet-scale
diagnosability literature (arxiv 2510.20171) is blunt that the signal
must aggregate across the fleet with bounded loss -- not be sampled
from one lucky process. Two obstacles:

* the registry's histograms are sample windows (bounded deques).
  Quantiles over sample windows do NOT merge: p95 of two windows is
  not the p95 of the union. :class:`LogBucketSketch` fixes this with
  log-spaced buckets (the DDSketch construction): the bucket index of
  value ``v`` is ``ceil(log_gamma v)`` with ``gamma = (1+alpha)/
  (1-alpha)``, so any quantile estimate is within relative error
  ``alpha`` of the true value, and merging two sketches is bucket-
  count addition -- associative, commutative, and loss-free.
* cross-process transport. We reuse the MorphChannel file idiom
  (resilience/signals.py): each publisher appends schema-stamped
  ``health_digest`` records to its own JSONL under
  ``$TPU_HPC_DIGEST_DIR`` (O_APPEND single-write atomicity; no
  coordination, no server), with flight-dump non-clobbering names so
  a restarted process never truncates its predecessor's evidence.

Counters in a digest are CUMULATIVE (each record carries the
publisher's totals so far), not per-period deltas: a reader that
misses a record, or reads the same record twice, still converges to
the right totals by keeping the latest ``seq`` per publisher -- the
idempotence that makes the aggregator's merge safe under replays and
arbitrary interleavings (property-tested in tests/test_live.py).
"""
from __future__ import annotations

import json
import math
import os
import time
from typing import Dict, List, Mapping, Optional, Tuple

ENV_DIGEST_DIR = "TPU_HPC_DIGEST_DIR"

# Pinned default relative-error bound for digest sketches. 1% is tight
# enough that a merged fleet p99 is operationally the p99, and coarse
# enough that a sketch spanning nanoseconds..hours stays ~a few
# thousand buckets.
DEFAULT_ALPHA = 0.01

# Values at or below this land in the zero bucket: log-bucketing can't
# represent 0, and sub-picosecond durations are measurement noise.
_ZERO_EPS = 1e-12


class LogBucketSketch:
    """DDSketch-style log-bucketed histogram with relative-error
    bound ``alpha``.

    ``add(v)`` maps v to bucket ``k = ceil(log_gamma v)``; the bucket's
    representative value ``2*gamma^k / (gamma+1)`` (the midpoint of
    ``(gamma^(k-1), gamma^k]``) is within ``alpha`` relative error of
    every value in the bucket. ``merge`` adds bucket counts, so
    quantiles over the union of any number of streams are exact up to
    the same bound -- the property the fleet rollup is built on.
    Negative values are clamped to the zero bucket (durations and
    sizes; a negative sample is a producer bug, not a distribution).
    """

    __slots__ = ("alpha", "gamma", "_log_gamma", "buckets", "zero",
                 "count", "sum", "min", "max")

    def __init__(self, alpha: float = DEFAULT_ALPHA):
        if not (0.0 < alpha < 1.0):
            raise ValueError(f"alpha {alpha} must be in (0, 1)")
        self.alpha = float(alpha)
        self.gamma = (1.0 + alpha) / (1.0 - alpha)
        self._log_gamma = math.log(self.gamma)
        self.buckets: Dict[int, int] = {}
        self.zero = 0
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf

    def add(self, value: float, n: int = 1) -> None:
        v = float(value)
        if n < 1:
            raise ValueError(f"n {n} must be >= 1")
        self.count += n
        self.sum += v * n
        self.min = min(self.min, v)
        self.max = max(self.max, v)
        if v <= _ZERO_EPS:
            self.zero += n
            return
        k = math.ceil(math.log(v) / self._log_gamma)
        self.buckets[k] = self.buckets.get(k, 0) + n

    def merge(self, other: "LogBucketSketch") -> "LogBucketSketch":
        """In-place merge; returns self. Both sketches must share
        ``alpha`` (bucket boundaries are alpha-derived -- merging
        mismatched sketches would silently corrupt quantiles)."""
        if abs(other.alpha - self.alpha) > 1e-12:
            raise ValueError(
                f"cannot merge sketches with alpha {self.alpha} vs "
                f"{other.alpha}"
            )
        for k, n in other.buckets.items():
            self.buckets[k] = self.buckets.get(k, 0) + n
        self.zero += other.zero
        self.count += other.count
        self.sum += other.sum
        self.min = min(self.min, other.min)
        self.max = max(self.max, other.max)
        return self

    def _value_of(self, k: int) -> float:
        return 2.0 * self.gamma ** k / (self.gamma + 1.0)

    def quantile(self, q: float) -> float:
        """Nearest-rank quantile estimate; 0.0 on an empty sketch.
        Within ``alpha`` relative error of the exact nearest-rank
        quantile of everything ever added (across merges)."""
        if not (0.0 <= q <= 1.0):
            raise ValueError(f"q {q} must be in [0, 1]")
        if self.count == 0:
            return 0.0
        rank = max(0, math.ceil(q * self.count) - 1)
        if rank < self.zero:
            return 0.0
        seen = self.zero
        for k in sorted(self.buckets):
            seen += self.buckets[k]
            if rank < seen:
                return self._value_of(k)
        return self._value_of(max(self.buckets))

    def summary(self) -> Dict[str, float]:
        """The registry's histogram_summary shape plus p999 -- what a
        rollup row renders. min/max are exact (tracked outside the
        buckets), quantiles are alpha-bounded."""
        empty = self.count == 0
        return {
            "count": self.count,
            "sum": self.sum,
            "min": 0.0 if empty else self.min,
            "max": 0.0 if empty else self.max,
            "p50": self.quantile(0.50),
            "p95": self.quantile(0.95),
            "p99": self.quantile(0.99),
            "p999": self.quantile(0.999),
        }

    # -- wire form -----------------------------------------------------
    def to_dict(self) -> dict:
        """JSON-safe wire form. Buckets are emitted in sorted index
        order so equal sketches serialize byte-identically -- the
        property the merge tests (and deterministic --json rollups)
        lean on."""
        out: dict = {
            "alpha": self.alpha,
            "count": self.count,
            "sum": self.sum,
            "zero": self.zero,
            "buckets": {
                str(k): self.buckets[k] for k in sorted(self.buckets)
            },
        }
        if self.count:
            out["min"] = self.min
            out["max"] = self.max
        return out

    @classmethod
    def from_dict(cls, d: Mapping) -> "LogBucketSketch":
        sk = cls(alpha=float(d.get("alpha", DEFAULT_ALPHA)))
        sk.count = int(d.get("count", 0))
        sk.sum = float(d.get("sum", 0.0))
        sk.zero = int(d.get("zero", 0))
        sk.buckets = {
            int(k): int(n) for k, n in dict(d.get("buckets", {})).items()
        }
        if sk.count:
            sk.min = float(d.get("min", 0.0))
            sk.max = float(d.get("max", 0.0))
        return sk


def _non_clobbering(path: str) -> str:
    """Flight-dump naming discipline: never overwrite a predecessor's
    channel -- append ``.1``, ``.2``, ... until the name is free."""
    if not os.path.exists(path):
        return path
    i = 1
    while os.path.exists(f"{path}.{i}"):
        i += 1
    return f"{path}.{i}"


class DigestPublisher:
    """One process's periodic ``health_digest`` feed.

    Each ``publish*`` call stamps a schema-valid record through the
    event bus (so it also lands in the run log + flight ring with
    run_id/host/pid provenance) and appends the same record to this
    publisher's own channel file under ``dir`` -- the MorphChannel
    append idiom: makedirs-then-append, one ``write()`` per record, no
    locks. ``seq`` is monotonic per publisher; counters passed in must
    be cumulative (see module docstring).

    ``t`` is the publisher's notion of now -- the harnesses pass their
    virtual clock so a replayed run publishes bit-identical digests;
    wall-clock producers (the Trainer) default to ``time.time()``.
    """

    def __init__(
        self,
        dir: str,
        role: str,
        key: str,
        *,
        alpha: float = DEFAULT_ALPHA,
        period_s: Optional[float] = None,
        bus=None,
    ):
        if not role or not key:
            raise ValueError(
                f"role {role!r} and key {key!r} must be non-empty"
            )
        self.dir = dir
        self.role = role
        self.key = str(key)
        self.alpha = alpha
        self.period_s = period_s
        self._bus = bus
        safe = f"digest.{role}.{self.key}.pid{os.getpid()}.jsonl"
        os.makedirs(dir, exist_ok=True)
        self.path = _non_clobbering(os.path.join(dir, safe))
        self.seq = 0
        self.last_publish_t: Optional[float] = None

    @classmethod
    def from_env(
        cls, role: str, key: str, **kw
    ) -> Optional["DigestPublisher"]:
        """None when ``$TPU_HPC_DIGEST_DIR`` is unset -- the live plane
        is strictly opt-in; producers guard with ``if pub:``."""
        d = os.environ.get(ENV_DIGEST_DIR)
        if not d:
            return None
        return cls(d, role, key, **kw)

    def due(self, now: float) -> bool:
        """Rate limit helper: True when ``period_s`` has elapsed since
        the last publish (or on the first call / no period set)."""
        if self.period_s is None or self.last_publish_t is None:
            return True
        return now - self.last_publish_t >= self.period_s

    def publish(
        self,
        *,
        counters: Optional[Mapping[str, float]] = None,
        gauges: Optional[Mapping[str, float]] = None,
        hists: Optional[Mapping[str, LogBucketSketch]] = None,
        t: Optional[float] = None,
        step_s: Optional[float] = None,
        watermark_s: Optional[float] = None,
        step: Optional[int] = None,
        sink: Optional[str] = None,
    ) -> dict:
        """Build + emit + append one digest record; returns the
        stamped record. The build/append cost is metered: a
        ``digest_publish`` span plus the ``obs.digest_publish_ms``
        histogram the regress gate banks -- the plane's own overhead
        is gate-diffed like any other hot path."""
        from tpu_hpc.obs.events import get_bus
        from tpu_hpc.obs.registry import get_registry
        from tpu_hpc.obs.spans import emit_span

        t0 = time.perf_counter()
        bus = self._bus or get_bus()
        fields: dict = {
            "role": self.role,
            "key": self.key,
            "t": float(t if t is not None else time.time()),
            "seq": self.seq,
            "counters": {
                k: float(v) for k, v in sorted((counters or {}).items())
            },
            "gauges": {
                k: float(v) for k, v in sorted((gauges or {}).items())
            },
            "hists": {
                k: v.to_dict() for k, v in sorted((hists or {}).items())
            },
            "alpha": self.alpha,
        }
        if step_s is not None:
            fields["step_s"] = round(float(step_s), 4)
        if watermark_s is not None:
            fields["watermark_s"] = round(float(watermark_s), 4)
        if self.period_s is not None:
            fields["period_s"] = self.period_s
        rec = bus.emit("health_digest", sink=sink, step=step, **fields)
        # MorphChannel append idiom: one write, O_APPEND-atomic for
        # records far under PIPE_BUF-scale sizes.
        os.makedirs(self.dir, exist_ok=True)
        with open(self.path, "a") as f:
            f.write(json.dumps(rec, sort_keys=True) + "\n")
        self.seq += 1
        self.last_publish_t = fields["t"]
        dur = time.perf_counter() - t0
        emit_span("digest_publish", dur, bus=bus, step=step,
                  n=len(fields["hists"]))
        # NOT via emit_span's hist= (that observes seconds); this
        # histogram is ms-named and banked in ms.
        get_registry().observe(
            "obs.digest_publish_ms", dur * 1e3,
            help="health-digest build+append cost per publish (ms)",
        )
        return rec

    def publish_registry(
        self,
        registry=None,
        *,
        t: Optional[float] = None,
        step_s: Optional[float] = None,
        watermark_s: Optional[float] = None,
        step: Optional[int] = None,
        sink: Optional[str] = None,
    ) -> dict:
        """Digest the process-wide registry: counters + gauges verbatim,
        histograms from the registry's mergeable sketch backend (the
        sample windows stay process-local -- they can't merge)."""
        from tpu_hpc.obs.registry import get_registry

        reg = registry or get_registry()
        snap = reg.snapshot()
        return self.publish(
            counters=snap["counters"],
            gauges=snap["gauges"],
            hists=reg.sketch_snapshot(),
            t=t, step_s=step_s, watermark_s=watermark_s,
            step=step, sink=sink,
        )


def merge_digest_hists(
    records: List[Mapping],
) -> Dict[str, LogBucketSketch]:
    """Merge the ``hists`` payloads of digest records (each already the
    latest per publisher) into one sketch per histogram name."""
    out: Dict[str, LogBucketSketch] = {}
    for rec in records:
        for name, d in (rec.get("hists") or {}).items():
            sk = LogBucketSketch.from_dict(d)
            if name in out:
                out[name].merge(sk)
            else:
                out[name] = sk
    return out


def read_channel(path: str) -> List[dict]:
    """Read one digest channel file; skips blank lines, fails loudly
    on non-JSON (a torn channel is evidence corruption, not noise)."""
    records: List[dict] = []
    with open(path) as f:
        for lineno, line in enumerate(f, start=1):
            line = line.strip()
            if not line:
                continue
            try:
                records.append(json.loads(line))
            except ValueError as e:
                raise ValueError(
                    f"{path}:{lineno}: not JSON ({e})"
                ) from None
    return records


def read_digest_dir(dir: str) -> List[dict]:
    """Every ``health_digest`` record from every channel under
    ``dir`` (sorted filenames -- deterministic ingest order). Non-
    digest records in a channel are ignored: publishers share the
    directory with nothing, but defensiveness is cheap."""
    records: List[dict] = []
    try:
        names = sorted(os.listdir(dir))
    except FileNotFoundError:
        return records
    for name in names:
        if ".jsonl" not in name or not name.startswith("digest."):
            continue
        for rec in read_channel(os.path.join(dir, name)):
            if rec.get("event") == "health_digest":
                records.append(rec)
    return records
