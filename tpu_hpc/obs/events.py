"""The structured event bus + flight recorder.

One :class:`EventBus` per process: every record any subsystem emits is
(1) schema-stamped (run_id/host/pid/time, schema.py), (2) appended to a
bounded in-memory ring -- the **flight recorder** -- and (3) written to
a JSONL sink when one is configured (the bus's own ``path`` and/or a
per-emit ``sink``; the same file is never written twice for one
record).

The flight recorder answers the post-hoc forensics question every
crash report starts with: *what was the run doing right before it
died?* The ring holds the last ``ring_size`` events on every host (not
just host 0 -- the host that wedges is rarely the one writing the run
log), and :meth:`EventBus.dump_flight` writes it to disk. The dump is
wired into the three ways a run dies abnormally:

* SIGTERM / preemption notice -- resilience/signals.PreemptionGuard;
* hang-watchdog fire          -- resilience/heartbeat.HangWatchdog;
* injected fault (hard kill)  -- resilience/faults.FaultPlan.

Dumps go to ``TPU_HPC_FLIGHT_DIR`` (the supervisor exports its
``--log-dir`` so flight evidence lands next to the attempt logs) or an
explicitly configured ``flight_dir``; with neither, dumping is a no-op
-- an unconfigured process must not litter its cwd. The Trainer points
the dir at its checkpoint directory, where the hang dumps already go.
Filenames are non-clobbering (``flight.<reason>.pid<N>.jsonl[.k]``):
a restart loop must never overwrite the previous attempt's evidence
(the round-5 overwritten-OOM-log lesson, VERDICT item 9).
"""
from __future__ import annotations

import collections
import json
import os
import re
import socket
import threading
import time
import uuid
from typing import Deque, Iterable, Optional

from tpu_hpc.obs.schema import stamp

ENV_RUN_ID = "TPU_HPC_RUN_ID"
ENV_EVENTS = "TPU_HPC_EVENTS"
ENV_FLIGHT_DIR = "TPU_HPC_FLIGHT_DIR"

DEFAULT_RING_SIZE = 512

# Ambient trace context (obs/trace.py activate()): while a trace is
# active on a thread, every emit on that thread is stamped with its
# trace_id -- so a scheduler that activates a request's context around
# an engine call gets the engine's internal spans and kv_block events
# correlated for free, without threading the id through every layer.
# Lives HERE (not in trace.py) so the per-emit lookup is one
# thread-local getattr with no import indirection on the hot path.
_TRACE = threading.local()


def current_trace_id() -> Optional[str]:
    """The thread's active trace id, or None."""
    return getattr(_TRACE, "trace_id", None)


_hostname: Optional[str] = None


def _host() -> str:
    global _hostname
    if _hostname is None:
        try:
            _hostname = socket.gethostname()
        except OSError:  # pragma: no cover - degenerate environments
            _hostname = "unknown"
    return _hostname


def gen_run_id() -> str:
    """Sortable-by-start-time, collision-safe run identifier."""
    return (
        time.strftime("%Y%m%d-%H%M%S")
        + f"-{os.getpid()}-{uuid.uuid4().hex[:6]}"
    )


class EventBus:
    """Process-local telemetry bus: stamp, ring, sink.

    ``path`` (default ``$TPU_HPC_EVENTS``): JSONL file every emit is
    appended to. ``run_id`` (default ``$TPU_HPC_RUN_ID``, else
    generated): stamped on every record so multi-attempt/multi-host
    artifacts join on it. ``flight_dir`` (default
    ``$TPU_HPC_FLIGHT_DIR``): where :meth:`dump_flight` writes; None
    disables dumping until a caller configures it.
    """

    def __init__(
        self,
        path: Optional[str] = None,
        run_id: Optional[str] = None,
        ring_size: int = DEFAULT_RING_SIZE,
        flight_dir: Optional[str] = None,
    ):
        if ring_size < 1:
            raise ValueError(f"ring_size {ring_size} must be >= 1")
        env = os.environ
        self.path = path if path is not None else env.get(ENV_EVENTS)
        self.run_id = run_id or env.get(ENV_RUN_ID) or gen_run_id()
        self.flight_dir = (
            flight_dir if flight_dir is not None
            else env.get(ENV_FLIGHT_DIR)
        )
        self._ring: Deque[dict] = collections.deque(maxlen=ring_size)
        # Reentrant: dump_flight may run from a signal handler that
        # interrupted the main thread mid-emit (PreemptionGuard's
        # on_trigger hook) -- a plain Lock would self-deadlock there.
        self._lock = threading.RLock()

    # -- emission ------------------------------------------------------
    def emit(self, event: str, sink: Optional[str] = None, **fields) -> dict:
        """Stamp + ring + write one record. ``sink`` is an extra JSONL
        file for this record (the Trainer routes its run log here);
        None-valued fields are dropped so optional context never
        serializes as ``null``."""
        rec = {
            "event": event,
            **{k: v for k, v in fields.items() if v is not None},
        }
        return self.emit_record(rec, sink=sink)

    def emit_record(self, record: dict, sink: Optional[str] = None) -> dict:
        """Emit a pre-built record (must carry ``event``); stamps the
        missing provenance fields without overwriting present ones."""
        rec = stamp(
            record, run_id=self.run_id, host=_host(), pid=os.getpid()
        )
        # Ambient trace stamping: an explicit trace_id always wins; a
        # record emitted while a trace is active on this thread joins
        # it. One thread-local read -- ring-only hot paths stay cheap.
        if "trace_id" not in rec:
            tid = current_trace_id()
            if tid is not None:
                rec["trace_id"] = tid
        with self._lock:
            self._ring.append(rec)
        # File I/O happens OUTSIDE the ring lock: a sink on a hung
        # filesystem must not wedge every other thread's emit (or the
        # watchdog's ring snapshot) behind it. Whole-line O_APPEND
        # writes don't interleave, and every record carries its own
        # timestamp, so relaxed cross-thread file order costs nothing.
        # A set: bus path and per-emit sink may be the same file (the
        # serve replay points both at the run JSONL) -- one record
        # must land once. Serialization is skipped entirely for
        # ring-only emits: hot paths (a span per decode step) pay one
        # deque append, not a json.dumps. Falsy paths are dropped
        # too: "" is the documented "off" spelling
        # (TrainingConfig.metrics_path) and a set-but-empty
        # $TPU_HPC_EVENTS must disable the sink, not crash every emit
        # on open("").
        paths = {self.path, sink} - {None, ""}
        if paths:
            line = json.dumps(rec)
            for p in paths:
                parent = os.path.dirname(p)
                if parent:
                    os.makedirs(parent, exist_ok=True)
                with open(p, "a") as f:
                    f.write(line + "\n")
        return rec

    # -- flight recorder -----------------------------------------------
    def ring(
        self, lock_timeout: Optional[float] = None
    ) -> Iterable[dict]:
        """Snapshot of the in-memory ring, oldest first.

        ``lock_timeout`` bounds the wait for the ring lock, then falls
        back to a lockless best-effort copy -- the hang watchdog's
        dump path must never block behind a thread wedged mid-emit
        (it still has an os._exit to deliver)."""
        if lock_timeout is None:
            acquired = self._lock.acquire()
        else:
            acquired = self._lock.acquire(timeout=lock_timeout)
        if acquired:
            try:
                return list(self._ring)
            finally:
                self._lock.release()
        try:
            return list(self._ring)
        except RuntimeError:  # pragma: no cover - mutated mid-copy
            return []

    def dump_flight(
        self, reason: str, path: Optional[str] = None
    ) -> Optional[str]:
        """Write the ring to disk: a ``flight_dump`` header record
        followed by the buffered events, oldest first. Returns the
        path written, or None when no destination is configured or the
        write fails (dumping is diagnostics -- it must never turn a
        dying run's last act into a new crash)."""
        try:
            if path is None:
                if not self.flight_dir:  # None or "" = disabled
                    return None
                safe = re.sub(r"[^A-Za-z0-9_.-]", "_", reason) or "dump"
                path = os.path.join(
                    self.flight_dir,
                    f"flight.{safe}.pid{os.getpid()}.jsonl",
                )
            base, k = path, 0
            while os.path.exists(path):
                k += 1
                path = f"{base}.{k}"
            parent = os.path.dirname(path)
            if parent:
                os.makedirs(parent, exist_ok=True)
            events = self.ring(lock_timeout=2.0)
            header = stamp(
                {
                    "event": "flight_dump",
                    "reason": reason,
                    "n_events": len(events),
                },
                run_id=self.run_id, host=_host(), pid=os.getpid(),
            )
            with open(path, "w") as f:
                f.write(json.dumps(header) + "\n")
                for rec in events:
                    f.write(json.dumps(rec) + "\n")
            return path
        except OSError:  # pragma: no cover - diagnostics best-effort
            return None


_BUS: Optional[EventBus] = None
# RLock for the same reason as EventBus._lock: a signal handler that
# dumps the ring (PreemptionGuard.flight_reason) re-enters get_bus()
# on the very thread that may already hold this lock mid-emit.
_BUS_LOCK = threading.RLock()


def get_bus() -> EventBus:
    """The process-wide bus, created lazily from the env contract."""
    global _BUS
    with _BUS_LOCK:
        if _BUS is None:
            _BUS = EventBus()
        return _BUS


def set_bus(bus: Optional[EventBus]) -> Optional[EventBus]:
    """Install ``bus`` as the process-wide bus; returns the previous
    one so scoped users (the serve replay, tests) can restore it."""
    global _BUS
    with _BUS_LOCK:
        prev, _BUS = _BUS, bus
        return prev


def dump_flight(reason: str, path: Optional[str] = None) -> Optional[str]:
    """Module-level convenience: dump the current bus's ring. The hook
    the resilience layer calls from signal handlers / watchdog threads
    (hence the blanket best-effort contract of EventBus.dump_flight)."""
    return get_bus().dump_flight(reason, path=path)
