"""``python -m tpu_hpc.obs.bank BENCH_r*.json -o BENCH_HISTORY.jsonl``
-- normalize the banked bench history.

The driver's per-round captures (``BENCH_r01.json`` ...) are ad-hoc
``{n, cmd, rc, tail, parsed}`` wrappers: the parsed bench record when
the round succeeded, a raw stderr tail when the backend was out. Four
of five rounds on record are outages, and the one schema any gate can
trust is obs/schema.py's -- so this converter lifts every capture into
one validated ``bench``-event JSONL:

* a successful round's ``parsed`` record becomes a ``bench`` event
  (metric/value/unit + whatever rode along), stamped with its round
  number, exit code and source file;
* a failed round becomes the same failure row ``bench.py --all``
  already emits (``value: null, unit: "FAILED"``, last stderr line as
  ``error``) -- outages are part of the trajectory, not silently
  dropped history;
* an ``MFU <x>%`` figure in the tail (the human headline line) is
  lifted into an ``mfu`` field so the bank keeps the number the
  PERFORMANCE.md table quotes.

Builder-recorded row files (``BENCH_EXTRA.jsonl``,
``HW_QUEUE_r05/bench_*.json`` single records) are accepted too: any
input that is already a bench record (or JSONL of them) is stamped and
passed through. The output is the ONE trusted input
``python -m tpu_hpc.obs.regress --bank`` diffs candidates against.
"""
from __future__ import annotations

import argparse
import json
import os
import re
import sys
from typing import List, Optional, Sequence

from tpu_hpc.obs.schema import stamp, validate_record

_MFU_RE = re.compile(r"MFU (\d+(?:\.\d+)?)%")


def _lift_record(raw: dict, source: str, extra: dict) -> dict:
    """A record that already looks like a bench row -> stamped bench
    event."""
    rec = {"event": "bench", **raw, **extra, "source": source}
    return stamp(rec)


def lift_capture(data: dict, source: str) -> dict:
    """One driver capture ``{n, cmd, rc, tail, parsed}`` -> one
    stamped ``bench`` event."""
    extra = {"round": data.get("n"), "rc": data.get("rc")}
    tail = data.get("tail") or ""
    m = _MFU_RE.findall(tail)
    if m:
        extra["mfu"] = float(m[-1]) / 100.0
    parsed = data.get("parsed")
    if isinstance(parsed, dict) and "value" in parsed \
            and "unit" in parsed:
        return _lift_record(parsed, source, extra)
    err_lines = [l for l in tail.strip().splitlines() if l.strip()]
    return stamp({
        "event": "bench",
        "metric": "driver_bench",
        "value": None,
        "unit": "FAILED",
        "error": err_lines[-1][-300:] if err_lines else "no output",
        **extra,
        "source": source,
    })


def lift_file(path: str) -> List[dict]:
    """Lift one input file: a driver capture, a single bench record,
    or a JSONL of bench records."""
    source = os.path.basename(path)
    with open(path) as f:
        text = f.read()
    out: List[dict] = []
    try:
        data = json.loads(text)
    except ValueError:
        data = None
    if isinstance(data, dict):
        if "tail" in data or "parsed" in data:
            out.append(lift_capture(data, source))
        elif "metric" in data and "value" in data:
            out.append(_lift_record(data, source, {}))
        else:
            raise ValueError(
                f"{path}: neither a driver capture nor a bench record"
            )
    else:
        # JSONL of bench rows (BENCH_EXTRA.jsonl style).
        for lineno, line in enumerate(text.splitlines(), start=1):
            line = line.strip()
            if not line:
                continue
            try:
                row = json.loads(line)
            except ValueError as e:
                raise ValueError(f"{path}:{lineno}: not JSON ({e})")
            if not isinstance(row, dict) or "metric" not in row:
                raise ValueError(
                    f"{path}:{lineno}: not a bench record"
                )
            out.append(_lift_record(row, source, {}))
    for rec in out:
        validate_record(rec)
    return out


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="tpu_hpc.obs.bank",
        description=__doc__.split("\n")[0],
    )
    ap.add_argument(
        "inputs", nargs="+",
        help="driver captures (BENCH_rNN.json), bench records, or "
        "bench-row JSONLs",
    )
    ap.add_argument(
        "-o", "--out", default="BENCH_HISTORY.jsonl",
        help="output JSONL (default BENCH_HISTORY.jsonl)",
    )
    args = ap.parse_args(argv)
    records: List[dict] = []
    for path in args.inputs:
        try:
            records.extend(lift_file(path))
        except (OSError, ValueError) as e:
            print(f"tpu_hpc.obs.bank: {e}", file=sys.stderr)
            return 2
    with open(args.out, "w") as f:
        for rec in records:
            f.write(json.dumps(rec) + "\n")
    print(
        f"tpu_hpc.obs.bank: wrote {len(records)} validated bench "
        f"record(s) to {args.out}",
        file=sys.stderr,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
