"""The one quantile implementation every latency number comes from.

Before this module, obs/registry.py and serve/metrics.py each carried
a private nearest-rank ``_quantile`` helper. Two copies of
almost-the-same estimator is exactly how a regression gate ends up
comparing a p95 computed one way against a p95 computed another; the
regress driver (obs/regress.py) stakes exit codes on these numbers, so
they are computed in ONE place, with the standard linear-interpolation
estimator (numpy's default ``percentile`` method) and pinned against
``np.percentile`` on known distributions in tests/test_obs.py.
"""
from __future__ import annotations

from typing import Dict, Iterable, Sequence, Tuple

DEFAULT_QS: Tuple[float, ...] = (0.50, 0.95, 0.99)


def quantile(sorted_vals: Sequence[float], q: float) -> float:
    """Linear-interpolation quantile of an ascending-sorted sequence
    (numpy's default method: index ``q * (n - 1)``, interpolated).
    Empty input returns 0.0 -- the registry/meter convention for "no
    samples yet" (summaries must render, not crash, mid-warmup)."""
    n = len(sorted_vals)
    if n == 0:
        return 0.0
    if not 0.0 <= q <= 1.0:
        raise ValueError(f"quantile q {q} must be in [0, 1]")
    pos = q * (n - 1)
    lo = int(pos)
    hi = min(lo + 1, n - 1)
    frac = pos - lo
    return sorted_vals[lo] * (1.0 - frac) + sorted_vals[hi] * frac


def summarize(
    values: Iterable[float], qs: Sequence[float] = DEFAULT_QS,
    prefix: str = "p",
) -> Dict[str, float]:
    """``{"p50": ..., "p95": ..., "p99": ...}`` over ``values``
    (sorted internally)."""
    vals = sorted(values)
    return {
        f"{prefix}{round(q * 100):d}": quantile(vals, q) for q in qs
    }
