"""Counters / gauges / histograms with JSONL snapshots and Prometheus
text exposition.

One process-wide :class:`MetricsRegistry` that training (ThroughputMeter,
GoodputMeter), serving (ServeMeter, the engine's prefill/decode
timers) and the scheduler all publish into -- so MFU, TTFT/ITL and
goodput live in ONE namespace with one export path instead of three
private dicts. Two consumers:

* ``emit_snapshot()`` -- a ``metrics`` event through the bus (the
  Trainer appends one at run_end, so the run JSONL closes with the
  final counter state);
* ``prometheus_text()`` / ``write_prometheus()`` -- the standard text
  exposition format, atomically rewritten to ``$TPU_HPC_PROM_FILE``
  for a node-exporter textfile collector or a sidecar to scrape (no
  HTTP server in the training process: a wedged run must not also
  wedge a metrics port).

Histograms are windowed (bounded deques): the registry must be safe to
leave on for a million-step run, the same discipline the flight
recorder ring follows.
"""
from __future__ import annotations

import collections
import os
import re
import threading
from typing import Deque, Dict, Optional

from tpu_hpc.obs.digest import LogBucketSketch
from tpu_hpc.obs.quantiles import quantile as _quantile

ENV_PROM_FILE = "TPU_HPC_PROM_FILE"

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def _sanitize(name: str) -> str:
    """Prometheus metric-name charset; JSONL keeps the raw name."""
    out = _NAME_RE.sub("_", name)
    return out if not out[:1].isdigit() else "_" + out


class MetricsRegistry:
    """Thread-safe metrics store. ``hist_window`` bounds each
    histogram's sample memory (the summary is over the most recent
    window, which is what an operator alarming on p95 wants anyway)."""

    def __init__(
        self, hist_window: int = 4096, sketch_alpha: float = 0.01,
    ):
        if hist_window < 1:
            raise ValueError(f"hist_window {hist_window} must be >= 1")
        self.hist_window = hist_window
        self.sketch_alpha = sketch_alpha
        self._counters: Dict[str, float] = {}
        self._gauges: Dict[str, float] = {}
        self._hists: Dict[str, Deque[float]] = {}
        # Mergeable log-bucket sketches (obs/digest.py) fed alongside
        # the sample windows: the window answers "recent p95 here",
        # the sketch answers "fleet p99.9 across every process" --
        # window quantiles cannot merge, sketch quantiles can.
        self._sketches: Dict[str, "LogBucketSketch"] = {}
        self._help: Dict[str, str] = {}
        self._lock = threading.Lock()

    # -- writes --------------------------------------------------------
    def describe(self, name: str, help: str) -> None:
        """Attach HELP text to a metric (the Prometheus exposition
        emits it as a ``# HELP`` line). First description wins --
        producers re-describing on a hot path pay one dict lookup."""
        with self._lock:
            self._help.setdefault(name, help)

    def inc(
        self, name: str, value: float = 1.0,
        help: Optional[str] = None,
    ) -> None:
        if value < 0:
            raise ValueError(
                f"counter {name!r} increment {value} must be >= 0 "
                "(use a gauge for values that go down)"
            )
        if help is not None:
            self.describe(name, help)
        with self._lock:
            self._counters[name] = self._counters.get(name, 0.0) + value

    def set_gauge(
        self, name: str, value: float, help: Optional[str] = None,
    ) -> None:
        if help is not None:
            self.describe(name, help)
        with self._lock:
            self._gauges[name] = float(value)

    def observe(
        self, name: str, value: float, help: Optional[str] = None,
    ) -> None:
        if help is not None:
            self.describe(name, help)
        with self._lock:
            hist = self._hists.get(name)
            if hist is None:
                hist = self._hists[name] = collections.deque(
                    maxlen=self.hist_window
                )
                self._sketches[name] = LogBucketSketch(
                    alpha=self.sketch_alpha
                )
            hist.append(float(value))
            self._sketches[name].add(float(value))

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._hists.clear()
            self._sketches.clear()
            self._help.clear()

    # -- reads ---------------------------------------------------------
    def counter(self, name: str) -> float:
        with self._lock:
            return self._counters.get(name, 0.0)

    def gauge(self, name: str) -> Optional[float]:
        with self._lock:
            return self._gauges.get(name)

    def histogram_summary(self, name: str) -> Dict[str, float]:
        with self._lock:
            vals = sorted(self._hists.get(name, ()))
        return {
            "count": len(vals),
            "sum": sum(vals),
            "min": vals[0] if vals else 0.0,
            "max": vals[-1] if vals else 0.0,
            "p50": _quantile(vals, 0.50),
            "p95": _quantile(vals, 0.95),
            "p99": _quantile(vals, 0.99),
            "p999": _quantile(vals, 0.999),
        }

    def sketch_snapshot(self) -> Dict[str, LogBucketSketch]:
        """Copies of the mergeable sketches, one per histogram -- the
        payload a DigestPublisher ships. Copies, not references: the
        caller serializes outside the lock while producers keep
        observing."""
        with self._lock:
            return {
                n: LogBucketSketch.from_dict(sk.to_dict())
                for n, sk in self._sketches.items()
            }

    def snapshot(self) -> Dict[str, Dict]:
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            hist_names = list(self._hists)
        return {
            "counters": counters,
            "gauges": gauges,
            "histograms": {
                n: self.histogram_summary(n) for n in hist_names
            },
        }

    def emit_snapshot(
        self, bus=None, sink: Optional[str] = None,
        step: Optional[int] = None,
    ) -> dict:
        """One ``metrics`` event holding the full snapshot."""
        from tpu_hpc.obs.events import get_bus

        return (bus or get_bus()).emit(
            "metrics", sink=sink, metrics=self.snapshot(), step=step
        )

    # -- Prometheus text exposition ------------------------------------
    def prometheus_text(self, prefix: str = "tpu_hpc") -> str:
        """Counters/gauges as their native types; histograms as
        summaries (p50/p95/p99 quantiles + _sum/_count). Described
        metrics get a ``# HELP`` line ahead of ``# TYPE`` (exposition
        format: HELP text escapes ``\\`` and newlines) -- a scrape
        surface an operator can read without the source."""
        snap = self.snapshot()
        with self._lock:
            helps = dict(self._help)

        def head(name: str, m: str, kind: str) -> list:
            out = []
            text = helps.get(name)
            if text:
                text = text.replace("\\", "\\\\").replace("\n", "\\n")
                out.append(f"# HELP {m} {text}")
            out.append(f"# TYPE {m} {kind}")
            return out

        lines = []
        for name, val in sorted(snap["counters"].items()):
            m = f"{prefix}_{_sanitize(name)}"
            lines += head(name, m, "counter") + [f"{m} {val}"]
        for name, val in sorted(snap["gauges"].items()):
            m = f"{prefix}_{_sanitize(name)}"
            lines += head(name, m, "gauge") + [f"{m} {val}"]
        for name, s in sorted(snap["histograms"].items()):
            m = f"{prefix}_{_sanitize(name)}"
            lines += head(name, m, "summary") + [
                f'{m}{{quantile="0.5"}} {s["p50"]}',
                f'{m}{{quantile="0.95"}} {s["p95"]}',
                f'{m}{{quantile="0.99"}} {s["p99"]}',
                f'{m}{{quantile="0.999"}} {s["p999"]}',
                f"{m}_sum {s['sum']}",
                f"{m}_count {s['count']}",
            ]
        return "\n".join(lines) + ("\n" if lines else "")

    def write_prometheus(
        self, path: Optional[str] = None, prefix: str = "tpu_hpc"
    ) -> Optional[str]:
        """Atomically rewrite the exposition file (textfile-collector
        contract: readers must never see a torn scrape). ``path``
        defaults to ``$TPU_HPC_PROM_FILE``; with neither, a no-op."""
        path = path or os.environ.get(ENV_PROM_FILE)
        if not path:
            return None
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            f.write(self.prometheus_text(prefix))
        os.replace(tmp, path)
        return path


_REGISTRY: Optional[MetricsRegistry] = None
# RLock, matching events._BUS_LOCK: signal-handler telemetry may
# re-enter get_registry() on a thread already holding it.
_REGISTRY_LOCK = threading.RLock()


def get_registry() -> MetricsRegistry:
    """The process-wide registry, created lazily."""
    global _REGISTRY
    with _REGISTRY_LOCK:
        if _REGISTRY is None:
            _REGISTRY = MetricsRegistry()
        return _REGISTRY


def set_registry(
    registry: Optional[MetricsRegistry],
) -> Optional[MetricsRegistry]:
    """Swap the process-wide registry; returns the previous one."""
    global _REGISTRY
    with _REGISTRY_LOCK:
        prev, _REGISTRY = _REGISTRY, registry
        return prev
