"""``python -m tpu_hpc.obs.regress baseline.jsonl candidate.jsonl`` --
the perf-regression gate.

Every perf claim in this repo's history was a headline number, and the
BENCH_r01..r05 trajectory (46.3% -> 57.6% MFU with four driver-bench
outages in between) shows how easily one number lies. This gate
replaces it: two schema-stamped run JSONLs (a training run log, a
serve replay trace, or a tpu_hpc.loadgen run) are reduced through
``obs.report.build_report`` to their quantile metrics -- TTFT/ITL
p50/p95/p99, goodput, MFU, tokens/s, per-tenant loadgen quantiles,
shed counts, occupancy -- and diffed metric by metric against
per-metric tolerances. Exit is non-zero on ANY violated metric, named
with its quantile, so CI can gate a PR on measured distributions
instead of a headline (the DDP/FSDP characterization study's
discipline, arxiv 2505.12832).

Modes:

* default -- both files are run JSONLs; their reports are compared.
* ``--bank`` -- the baseline is a normalized bench-history JSONL
  (``python -m tpu_hpc.obs.bank`` lifts the BENCH_r*.json driver
  captures into one), the candidate holds new ``bench`` records;
  each candidate metric (its LATEST record per metric -- the round
  under judgment, never masked by a better earlier row in the same
  file) is compared against the bank's best value for that metric
  (the trajectory's high-water mark, not whichever round happened to
  run last).

SLO config (``--slo slo.json``)::

    {"default_tol": 0.1,
     "metrics": {"serve.ttft_ms_p95": {"tol": 0.05, "max": 200.0},
                 "goodput":           {"min": 0.85}}}

``tol`` is the relative regression allowed vs baseline; ``max``/
``min`` are absolute bounds on the candidate alone (true SLOs -- they
fire even when the baseline was already out of bounds, and a bound on
a metric the candidate never produced is itself a violation: a typoed
name must not silently never fire).

Exit codes (pinned by tests): 0 = gate passes, 1 = regression or SLO
violation (each printed as ``REGRESSION: <metric> ...``), 2 = unusable
input (missing/empty/schema-invalid file, or no comparable metrics --
a gate with nothing to compare must fail loudly, not pass silently).
"""
from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List, Optional, Sequence, Tuple

from tpu_hpc.obs.report import build_report
from tpu_hpc.obs.schema import SCHEMA_VERSION, SchemaError, load_records

DEFAULT_TOL = 0.10

# Substrings marking a metric as lower-is-better; everything else
# (throughput, goodput, MFU, occupancy) regresses by going DOWN.
# "wire_bytes"/"inflight": reshard-cost metrics (comm/bench.py's
# reshard rows) -- more bytes over the wire or a higher transient peak
# is the regression, so the bank diff catches a plan that started
# moving or materializing more than its history.
# "rollback"/"fallback"/"poisoned"/"spike"/"skipped"/"lost_steps"/
# "integrity_fail": the robustness counters (resilience.guard +
# ckpt.integrity) -- more guard rollbacks, skipped updates, silent
# restore fallbacks or checksum failures IS the regression, so the
# --bank gate fails on robustness drift, not just perf.
# Paged-KV cache efficiency (serve/paging.py): "stall" already
# covers serve.block_stalls (admissions waiting on the page pool --
# more stalls means the cache got less efficient at the same
# traffic); the prefix-cache gains ride the default direction --
# "prefix_hit*" matches no token here, so a DROPPING hit rate is the
# regression (higher-is-better), which is how the --bank gate
# catches cache-efficiency drift.
# Speculative decoding (serve/spec.py): "rejected" lower-is-better
# (more rejected drafts at the same traffic = a worse draft source);
# "draft_ms" rides the "_ms" token (a costlier draft is the
# regression); "acceptance_rate"/"accepted" match NO token here, so
# they judge higher-is-better by absence -- a dropping acceptance
# rate fails the --bank gate exactly like a dropping prefix-hit
# rate. All four pinned in tests/test_regress.py so the speculative
# rows are judged, never skipped.
_LOWER_IS_BETTER = (
    "ttft", "itl", "_ms", "latency", "shed", "stall", "queued",
    "wire_bytes", "inflight", "rejected",
    "rollback", "fallback", "poisoned", "spike", "skipped",
    "lost_steps", "lost_requests", "integrity_fail", "nonfinite",
    # HBM high-water mark (the device_memory events): a higher peak
    # at the same workload is a memory regression -- the fit-check's
    # budget erodes before anything OOMs.
    "hbm_peak",
    # Serving-fleet robustness counters (serve/fleet.py): more
    # redispatched requests, more replicas lost, or more swap
    # rollbacks at the same chaos schedule means failure handling
    # got worse -- the --bank gate fails on fleet-robustness drift
    # like it does on guard/ckpt drift.
    # "fleet.prefix_affinity_hit_rate" deliberately matches NO token
    # here: like prefix_hit_rate and acceptance_rate it judges
    # higher-is-better by absence -- a router change that cools the
    # per-replica tries fails the gate.
    "redispatch", "replica_down", "swap",
    # MPMD pipeline robustness (parallel/mpmd.py): more stages lost,
    # a fatter bubble, or a slower stage recovery at the same chaos
    # schedule is the regression -- the --bank gate fails on
    # pipeline-robustness drift like it does on fleet/guard drift.
    # ("redispatch" above already covers the replayed-microbatch
    # counter; "bubble" covers bubble_fraction, "mttr" covers
    # recovery_mttr_s.)
    "stage_down", "bubble", "mttr",
    # Topology morphing (tpu_hpc.elastic): more morphs at the same
    # chaos schedule, more wire bytes per transition, or a longer
    # quiesce-to-resume stall is the regression -- the --bank gate
    # fails on elastic drift like it does on pipeline/fleet drift.
    # ("wire_bytes" above already covers elastic.wire_bytes and the
    # morph_wire_bytes side key; "stall" covers elastic.stall_s;
    # "morph" covers the morph counters and the elastic_morph_*
    # headline rows.)
    "morph",
    # Host-DRAM KV tier (serve/tier.py): more pages crossing the
    # HBM/DRAM boundary at the same workload means the tier is
    # thrashing -- the --bank gate fails on spill/refill drift like
    # it does on morph drift. ("wire_bytes" above already covers the
    # kv_spill_wire_bytes / kv_refill_wire_bytes side keys; "ttft"
    # and "shed" cover ttft_on_return_ms_* and shed_on_return;
    # "resident_sessions" deliberately matches NO token -- like
    # prefix_hit_rate it judges higher-is-better by absence: a tier
    # change that sheds returning sessions fails the gate.)
    "spill", "refill",
    # Live telemetry plane (obs/digest, obs/live, obs/slo): more
    # burn-rate pages, more publishers going stale, or more flagged
    # stragglers at the same workload is a fleet-health regression;
    # "rel_err" covers the banked sketch quantile error bound -- a
    # sketch change that loosens the merge accuracy fails the gate.
    # ("slo_attainment" and "budget_remaining" deliberately match NO
    # token: higher-is-better by absence, like prefix_hit_rate.)
    "burn", "stale", "straggler", "rel_err",
    # Quantized KV pages (tpu_hpc.kernels.paged_attention): the
    # banked logit_rmse side key pins the int8 quantizer's
    # pre-softmax score error -- a quantizer change that widens the
    # drift fails the gate even while the latency headline still
    # rides within tolerance. ("kv_kernel"/"kv_quant" are identity,
    # carried in the metric family name, never judged.)
    "rmse",
)


def lower_is_better(name: str) -> bool:
    # Direction comes from the LEAF segment only: composite names
    # ("<headline metric>.<side key>", "loadgen.<tenant>.<stat>")
    # must not inherit the parent's tokens -- a banked
    # "..._ttft_ms_p95.acceptance_rate" is an acceptance rate
    # (higher-is-better), not a latency, and judging it by the
    # headline's "ttft" would wave a collapsing draft source through
    # the gate.
    low = name.lower().rsplit(".", 1)[-1]
    return any(tok in low for tok in _LOWER_IS_BETTER)


# -- metric extraction -------------------------------------------------
def report_metrics(rep: dict) -> Dict[str, float]:
    """Flatten a build_report() dict into the comparable numeric
    metrics namespace."""
    flat: Dict[str, float] = {}
    gp = rep.get("goodput")
    if gp:
        flat["goodput"] = float(gp["combined"]["goodput"])
    m = rep.get("mfu")
    if m:
        flat["mfu"] = float(m["mfu"])
    for key, val in (rep.get("serve") or {}).items():
        # "requests" is workload size; kv_block_size/kv_blocks are
        # pool CONFIG and kv_blocks_free_min follows it -- identity,
        # not performance; diffing them would fail the gate on a
        # deliberate re-size. prefill_chunks and the raw hit COUNTS
        # are excluded too: an IMPROVED prefix cache shortens chunk
        # plans (fewer chunks = better), which the default
        # higher-is-better direction would flag as a regression --
        # prefix_hit_rate (normalized, higher-is-better) and
        # block_stalls (lower) are the two cache-efficiency signals
        # the gate judges.
        # Speculative rows follow the same split (serve/spec.py):
        # spec_k is config, drafted/accepted/rejected/verify_steps
        # are raw counts that scale with the workload (an IMPROVED
        # acceptance rate means FEWER verify steps for the same
        # tokens, which a naive direction would flag) --
        # acceptance_rate (higher-is-better by token absence) and
        # draft_ms (lower, via "_ms") are the two judged speculative
        # signals.
        # Host-tier rows split the same way (serve/tier.py):
        # kv_host_blocks / kv_host_inflight_bytes are pool CONFIG
        # and kv_host_used/free follow it; the kv_spills/kv_refills
        # EVENT counts and the pages they carried are raw counts a
        # bigger workload inflates -- the judged tier signals are
        # the wire bytes (lower via "wire_bytes") and the hop
        # quantiles (lower via "_ms").
        if isinstance(val, (int, float)) and key not in (
            "requests", "kv_block_size", "kv_blocks",
            "kv_blocks_free_min", "prefill_chunks",
            "prefix_hits", "prefix_hit_blocks",
            "spec_k", "drafted", "accepted", "rejected",
            "verify_steps",
            "kv_host_blocks", "kv_host_used", "kv_host_free",
            "kv_host_inflight_bytes", "kv_spills", "kv_refills",
            "kv_spill_pages", "kv_refill_pages", "kv_host_drops",
        ):
            flat[f"serve.{key}"] = float(val)
    lg = rep.get("loadgen")
    if lg:
        for name, t in lg["tenants"].items():
            for k in ("ttft_ms_p50", "ttft_ms_p95", "ttft_ms_p99",
                      "itl_ms_p50", "itl_ms_p95"):
                if k in t:
                    flat[f"loadgen.{name}.{k}"] = float(t[k])
            flat[f"loadgen.{name}.shed"] = float(t["shed"])
            flat[f"loadgen.{name}.queued"] = float(t["queued"])
        for k in ("occupancy_mean", "occupancy_p95", "stall_events",
                  "shed", "queued"):
            if k in lg:
                flat[f"loadgen.{k}"] = float(lg[k])
    fl = rep.get("fleet")
    if fl:
        # The robustness counters are the judged signals (all
        # lower-is-better via the redispatch/replica_down/swap
        # tokens) plus the router's affinity outcome (higher by
        # absence). replicas / live range / scale decisions are
        # CONFIG-cum-behavior identity -- a deliberate re-size or a
        # different autoscale schedule must not fail the gate by
        # itself; its latency consequences already do.
        flat["fleet.replica_down"] = float(fl["replica_down"])
        flat["fleet.redispatched"] = float(fl["redispatched"])
        flat["fleet.swap_rollbacks"] = float(fl["swap_rollbacks"])
        if "prefix_affinity_hit_rate" in fl:
            flat["fleet.prefix_affinity_hit_rate"] = float(
                fl["prefix_affinity_hit_rate"]
            )
    pl = rep.get("pipeline")
    if pl:
        # The judged pipeline signals: stage losses, replays, bubble
        # and recovery MTTR (all lower-is-better via the
        # stage_down/redispatch/bubble/mttr tokens). The per-stage
        # timeline and straggler list are identity/behavior detail
        # the latency consequences already cover.
        flat["pipeline.stage_down"] = float(pl["stage_down"])
        flat["pipeline.redispatched"] = float(pl["redispatched"])
        if pl.get("bubble_fraction") is not None:
            flat["pipeline.bubble_fraction"] = float(
                pl["bubble_fraction"]
            )
        if pl.get("recovery_mttr_s") is not None:
            flat["pipeline.recovery_mttr_s"] = float(
                pl["recovery_mttr_s"]
            )
    el = rep.get("elastic")
    if el:
        # The judged elastic signals: morph count, total wire bytes
        # moved and total quiesce-to-resume stall (all lower-is-better
        # via the morph/wire_bytes/stall tokens). The per-morph
        # timeline is identity detail the totals already cover.
        flat["elastic.morphs"] = float(el["morphs"])
        flat["elastic.wire_bytes"] = float(el["wire_bytes"])
        flat["elastic.stall_s"] = float(el["stall_s"])
    g = rep.get("guard")
    if g:
        flat["guard.poisoned"] = float(g["poisoned"])
        flat["guard.spikes"] = float(g["spikes"])
        flat["guard.skipped"] = float(g["skipped"])
        flat["guard.rollbacks"] = float(len(g["rollbacks"]))
        flat["guard.lost_steps"] = float(g["lost_steps"])
    ck = rep.get("ckpt")
    if ck:
        flat["ckpt.fallbacks"] = float(ck["fallbacks"])
        flat["ckpt.integrity_failures"] = float(
            ck["integrity_failures"]
        )
    mem = rep.get("memory")
    if mem:
        # The HBM high-water mark (lower-is-better via "hbm_peak"):
        # a run whose peak grew against baseline fails the gate even
        # while latency holds.
        flat["memory.hbm_peak_bytes"] = float(mem["hbm_peak_bytes"])
    lv = rep.get("live")
    if lv:
        # The judged live-plane signals: stale publishers (lower via
        # "stale"), flagged stragglers (lower via "straggler"),
        # burn-rate pages (lower via "burn"), and SLO attainment /
        # budget remaining (higher-is-better by token absence). The
        # digest count and per-role tables are workload-size /
        # identity detail the verdict counters already cover.
        flat["live.digest_stale"] = float(lv["digest_stale"])
        flat["live.stragglers"] = float(len(lv.get("stragglers", [])))
        flat["slo.burns"] = float(lv["slo_burns"])
        if lv.get("slo_attainment") is not None:
            flat["slo.slo_attainment"] = float(lv["slo_attainment"])
        if lv.get("budget_remaining") is not None:
            flat["slo.budget_remaining"] = float(
                lv["budget_remaining"]
            )
    return flat


# Side metrics banked alongside a record's headline value: the
# latency quantiles, MFU -- and the speculative acceptance rate
# (serve/spec.py), the MECHANISM metric: a draft source going stale
# must fail the --bank gate even while the latency outcome still
# rides within tolerance. Producers lift these to the record's top
# level (bench.serve_record / loadgen_record); sub-dict fields are
# deliberately not walked.
_BANKED_SIDE_KEYS = (
    "ttft_ms_p50", "ttft_ms_p95", "ttft_ms_p99",
    "itl_ms_p50", "itl_ms_p95", "itl_ms_p99", "mfu",
    "acceptance_rate",
    # Fleet rows (serve/fleet.py): the router's prefix-affinity
    # outcome is the MECHANISM metric next to the latency headline --
    # a routing change that destroys per-replica trie warmth must
    # fail --bank even while the diurnal quantiles still ride within
    # tolerance (higher-is-better by token absence, like
    # acceptance_rate) -- and the robustness counters ride as side
    # keys too (producers lift them to the record top level;
    # sub-dict fields are deliberately not walked), so a chaos
    # schedule that starts losing replicas, replaying more requests
    # or rolling back swaps fails the gate even at equal latency.
    "prefix_affinity_hit_rate",
    "redispatched", "replica_down", "swap_rollbacks",
    "lost_requests",
    # MPMD pipeline rows (bench.py --pp-runtime mpmd): the measured
    # bubble and the stage-recovery MTTR ride next to the
    # tokens-per-second headline (both lower-is-better via the
    # "bubble"/"mttr" tokens) -- a runtime change that fattens the
    # bubble or slows recovery fails --bank even while throughput
    # still rides within tolerance. (The SPMD pp_* rows carry an
    # ANALYTIC bubble_fraction; it is schedule-determined and
    # constant at equal config, so judging it is a no-op there.)
    "bubble_fraction", "recovery_mttr_s",
    # int8 KV rows (tpu_hpc.kernels.paged_attention): the
    # deterministic quantizer-error pin rides next to the latency
    # headline (lower-is-better via the "rmse" token) -- see the
    # _LOWER_IS_BETTER note above.
    "logit_rmse",
    # Elastic rows (bench.py --workload elastic): the morph count and
    # total transition wire bytes ride next to the stall-seconds
    # headline (all lower-is-better via the "morph"/"wire_bytes"
    # tokens) -- a layout-policy change that starts moving more bytes
    # per transition fails --bank even while the stall headline still
    # rides within tolerance.
    "morphs", "morph_wire_bytes",
    # Host-tier rows (bench.py --serve-host-blocks, the
    # long_idle_sessions scenario): the returning-tenant latency
    # quantiles and shed count (lower via "ttft"/"shed"), the
    # resident-session count (higher by token absence), and the
    # cross-tier wire bytes (lower via "wire_bytes") all ride next
    # to the scenario's TTFT headline -- a tier change that sheds
    # returning sessions or starts thrashing pages across the
    # boundary fails --bank even while the headline holds.
    "ttft_on_return_ms_p50", "ttft_on_return_ms_p95",
    "shed_on_return", "resident_sessions",
    "kv_spill_wire_bytes", "kv_refill_wire_bytes",
)


def bank_metrics(
    records: Sequence[dict], keep: str = "best",
) -> Dict[str, float]:
    """Reduce a bench-record JSONL to one value per metric.

    ``keep="best"`` (the BASELINE side): max for higher-is-better,
    min for lower -- the trajectory's high-water mark.
    ``keep="latest"`` (the CANDIDATE side): the last record per
    metric in file order -- a candidate file holding several rounds
    must be judged by its newest measurement, or a regressed latest
    round hides behind any better earlier one (review finding).
    Failure rows (``value: null``) contribute nothing but are
    legitimate history."""
    if keep not in ("best", "latest"):
        raise ValueError(f"keep {keep!r} must be 'best' or 'latest'")
    out: Dict[str, float] = {}

    def consider(name: str, value) -> None:
        if not isinstance(value, (int, float)) or isinstance(
            value, bool
        ):
            return
        value = float(value)
        if keep == "latest" or name not in out:
            out[name] = value
        elif lower_is_better(name):
            out[name] = min(out[name], value)
        else:
            out[name] = max(out[name], value)

    for rec in records:
        if rec.get("event") != "bench":
            continue
        metric = rec.get("metric")
        if not metric:
            continue
        consider(metric, rec.get("value"))
        for k in _BANKED_SIDE_KEYS:
            if k in rec:
                consider(f"{metric}.{k}", rec[k])
    return out


# -- comparison --------------------------------------------------------
def load_slo(path: Optional[str]) -> dict:
    if path is None:
        return {}
    with open(path) as f:
        cfg = json.load(f)
    if not isinstance(cfg, dict):
        raise ValueError(f"{path}: SLO config must be a JSON object")
    return cfg


def compare(
    baseline: Dict[str, float],
    candidate: Dict[str, float],
    slo: Optional[dict] = None,
    tol: float = DEFAULT_TOL,
) -> Tuple[List[dict], int]:
    """Diff candidate against baseline; returns (violations, number of
    checks run). A metric present on only one side is skipped for the
    relative check (a new subsystem must not fail the gate for
    existing), but absolute SLO bounds apply to every candidate metric
    they name."""
    slo = slo or {}
    per_metric = slo.get("metrics", {})
    default_tol = float(slo.get("default_tol", tol))
    violations: List[dict] = []
    checked = 0
    for name in sorted(set(baseline) & set(candidate)):
        base, cand = baseline[name], candidate[name]
        m_tol = float(per_metric.get(name, {}).get("tol", default_tol))
        checked += 1
        if lower_is_better(name):
            limit = base * (1.0 + m_tol) + 1e-9
            bad = cand > limit
        else:
            limit = base * (1.0 - m_tol) - 1e-9
            bad = cand < limit
        if bad:
            violations.append({
                "metric": name,
                "kind": "regression",
                "baseline": base,
                "candidate": cand,
                "allowed": limit,
                "tol": m_tol,
                "direction": (
                    "lower" if lower_is_better(name) else "higher"
                ),
            })
    for name, bounds in per_metric.items():
        if name not in candidate:
            # An absolute bound on a metric the candidate never
            # produced is unverifiable -- a typoed name (or a config
            # pointed at the wrong run type) must fail the gate, not
            # silently never fire (review finding; same discipline as
            # parse_faults / TenantClass SLO-key validation).
            # tol-only entries are tolerance *modifiers* for the
            # relative pass and may legitimately cover metrics other
            # run types emit, so they skip quietly.
            if "max" in bounds or "min" in bounds:
                checked += 1
                violations.append({
                    "metric": name, "kind": "slo_missing",
                    "candidate": None,
                    "allowed": bounds.get("max", bounds.get("min")),
                })
            continue
        cand = candidate[name]
        # Every evaluated bound counts as a check, violated or not:
        # an SLO-only gate (no overlapping baseline metrics) whose
        # bounds all pass must exit 0, not "nothing to compare"
        # (review finding).
        if "max" in bounds:
            checked += 1
            if cand > float(bounds["max"]):
                violations.append({
                    "metric": name, "kind": "slo_max",
                    "candidate": cand,
                    "allowed": float(bounds["max"]),
                })
        if "min" in bounds:
            checked += 1
            if cand < float(bounds["min"]):
                violations.append({
                    "metric": name, "kind": "slo_min",
                    "candidate": cand,
                    "allowed": float(bounds["min"]),
                })
    return violations, checked


def _fmt_violation(v: dict) -> str:
    if v["kind"] == "regression":
        arrow = ">" if v["direction"] == "lower" else "<"
        return (
            f"REGRESSION: {v['metric']} {v['candidate']:.6g} {arrow} "
            f"allowed {v['allowed']:.6g} "
            f"(baseline {v['baseline']:.6g}, tol {v['tol']:.0%}, "
            f"{v['direction']}-is-better)"
        )
    if v["kind"] == "slo_missing":
        return (
            f"REGRESSION: {v['metric']} has an absolute SLO bound "
            "but the candidate produced no such metric (typoed name, "
            "or wrong run type for this SLO config?)"
        )
    bound = "max" if v["kind"] == "slo_max" else "min"
    return (
        f"REGRESSION: {v['metric']} {v['candidate']:.6g} violates "
        f"SLO {bound} {v['allowed']:.6g}"
    )


# -- CLI ---------------------------------------------------------------
def _metrics_from_file(
    path: str, bank: bool, keep: str = "best",
) -> Dict[str, float]:
    records = load_records(path, validate=True)
    if not records:
        raise SchemaError(f"{path} holds no records")
    if bank:
        return bank_metrics(records, keep=keep)
    return report_metrics(build_report(records))


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="tpu_hpc.obs.regress",
        description=__doc__.split("\n")[0],
    )
    ap.add_argument("baseline", help="baseline run JSONL (or, with "
                    "--bank, the normalized bench-history JSONL)")
    ap.add_argument("candidate", help="candidate run JSONL (or, with "
                    "--bank, a JSONL of new bench records)")
    ap.add_argument(
        "--bank", action="store_true",
        help="bench-history mode: compare candidate bench records "
        "against the bank's best value per metric",
    )
    ap.add_argument(
        "--slo", type=str, default=None,
        help="per-metric SLO/tolerance config (JSON; see module doc)",
    )
    ap.add_argument(
        "--tol", type=float, default=DEFAULT_TOL,
        help="default relative regression tolerance "
        f"(default {DEFAULT_TOL:.0%}; --slo overrides per metric)",
    )
    ap.add_argument("--json", action="store_true",
                    help="emit the verdict as one JSON object")
    args = ap.parse_args(argv)
    try:
        slo = load_slo(args.slo)
        base = _metrics_from_file(args.baseline, args.bank)
        # Candidate side of --bank: latest per metric, NOT best --
        # the newest round is the one under judgment.
        cand = _metrics_from_file(
            args.candidate, args.bank, keep="latest"
        )
    except (OSError, ValueError, SchemaError) as e:
        # SchemaError subclasses ValueError; both are "bad input".
        print(f"tpu_hpc.obs.regress: {e}", file=sys.stderr)
        if args.bank and "schema_version" in str(e):
            print(
                "hint: un-stamped bench rows (pre-schema history) "
                "must be lifted first: python -m tpu_hpc.obs.bank "
                "<file> -o lifted.jsonl",
                file=sys.stderr,
            )
        return 2
    violations, checked = compare(base, cand, slo=slo, tol=args.tol)
    if checked == 0:
        print(
            "tpu_hpc.obs.regress: no comparable metrics between "
            f"{args.baseline} and {args.candidate} -- a gate with "
            "nothing to check must not pass",
            file=sys.stderr,
        )
        return 2
    if args.json:
        print(json.dumps({
            "schema_version": SCHEMA_VERSION,
            "checked": checked,
            "violations": violations,
            "pass": not violations,
        }))
    else:
        for v in violations:
            print(_fmt_violation(v))
        verdict = "FAIL" if violations else "PASS"
        print(
            f"regress: {verdict} -- {checked} metric(s) checked, "
            f"{len(violations)} violation(s)"
        )
    return 1 if violations else 0


if __name__ == "__main__":
    sys.exit(main())
