"""Multi-window error-budget burn-rate alerting over the fleet rollup.

One bad minute must not page, and a slow leak must not hide: the
standard SRE construction is to alert on the *burn rate* of the error
budget -- ``(error rate) / (1 - target)`` -- over TWO windows at once.
The fast window catches an active incident quickly; the slow window
proves it is sustained; only when BOTH exceed the threshold is the
condition page-worthy. A transient spike trips the fast window alone
(no page); a slow regression trips the slow window alone until it
accelerates (no page); a real burn trips both.

:class:`BurnRateMonitor` feeds on the cumulative ``slo_good`` /
``slo_bad`` totals the rollup (obs/live.py) already sums across the
fleet, so the verdict is over what *every* replica saw, not one lucky
process. On page it emits one ``slo_burn`` record and arms the PR-13
:class:`~tpu_hpc.obs.trace.AnomalyCapture` trigger -- a burning SLO
yields one correlated evidence bundle (flight ring + memory snapshot)
keyed by trace_id, not a bare alert line. One-shot latched, like the
capture itself: an incident storm re-trips every tick, only the first
gets the page + bundle (``rearm()`` for multi-incident harnesses).

Time is whatever clock the caller observes on -- the serving harness
passes its virtual wall, so breach tests replay bit-identically.
"""
from __future__ import annotations

from typing import List, Optional, Tuple


class BurnRateMonitor:
    """Two-window error-budget burn monitor.

    ``observe(now, good, bad)`` takes CUMULATIVE totals (the digest
    counter discipline); the monitor differences them over each
    window. A window only judges once it is fully covered -- there is
    a sample at or before its left edge -- so a run shorter than the
    slow window can never page (no cold-start false positives).
    """

    def __init__(
        self,
        *,
        target: float = 0.99,
        fast_window_s: float = 60.0,
        slow_window_s: float = 600.0,
        threshold: float = 10.0,
        bus=None,
    ):
        if not (0.0 < target < 1.0):
            raise ValueError(f"target {target} must be in (0, 1)")
        if fast_window_s <= 0 or slow_window_s <= fast_window_s:
            raise ValueError(
                f"need 0 < fast_window_s {fast_window_s} < "
                f"slow_window_s {slow_window_s}"
            )
        if threshold <= 0:
            raise ValueError(f"threshold {threshold} must be > 0")
        self.target = target
        self.budget = 1.0 - target
        self.fast_window_s = fast_window_s
        self.slow_window_s = slow_window_s
        self.threshold = threshold
        self._bus = bus
        # (t, good_total, bad_total), append-only in observe order;
        # pruned to one sample at/behind the slow window's left edge.
        self._samples: List[Tuple[float, float, float]] = []
        self.fired = False
        self.burns = 0
        self.last_record: Optional[dict] = None

    # -- internals -----------------------------------------------------
    def _baseline(self, edge: float) -> Optional[Tuple[float, float, float]]:
        """Newest sample with t <= edge; None when the window is not
        yet covered by the observation history."""
        base = None
        for s in self._samples:
            if s[0] <= edge:
                base = s
            else:
                break
        return base

    def _window_rate(self, now: float, window_s: float,
                     good: float, bad: float) -> Optional[float]:
        base = self._baseline(now - window_s)
        if base is None:
            return None
        d_good = good - base[1]
        d_bad = bad - base[2]
        total = d_good + d_bad
        if total <= 0:
            return 0.0
        return d_bad / total

    def budget_remaining(self) -> Optional[float]:
        """Fraction of the whole-run error budget left (1.0 = untouched,
        0.0 = spent, negative = overspent); None before any traffic."""
        if not self._samples:
            return None
        _, good, bad = self._samples[-1]
        total = good + bad
        if total <= 0:
            return None
        return 1.0 - (bad / total) / self.budget

    # -- the monitor ---------------------------------------------------
    def observe(
        self,
        now: float,
        good: float,
        bad: float,
        *,
        sink: Optional[str] = None,
        trace_id: Optional[str] = None,
        capture=None,
        reason: Optional[str] = None,
    ) -> Optional[dict]:
        """Feed one rollup sample; returns the ``slo_burn`` record when
        this sample pages, else None. ``capture`` (AnomalyCapture) is
        triggered profiler-less (the post-run contract: the evidence
        is the fleet state, not a future step window)."""
        if self._samples and now < self._samples[-1][0]:
            raise ValueError(
                f"time went backwards: {now} < {self._samples[-1][0]}"
            )
        self._samples.append((float(now), float(good), float(bad)))
        # Prune: keep exactly one sample at/behind the slow edge (the
        # baseline) -- bounded memory for a million-tick run.
        edge = now - self.slow_window_s
        while (
            len(self._samples) >= 2 and self._samples[1][0] <= edge
        ):
            self._samples.pop(0)

        rate_fast = self._window_rate(
            now, self.fast_window_s, good, bad
        )
        rate_slow = self._window_rate(
            now, self.slow_window_s, good, bad
        )
        if rate_fast is None or rate_slow is None:
            return None
        burn_fast = rate_fast / self.budget
        burn_slow = rate_slow / self.budget
        if burn_fast < self.threshold or burn_slow < self.threshold:
            return None
        if self.fired:
            return None
        self.fired = True
        self.burns += 1

        from tpu_hpc.obs.events import get_bus

        bus = self._bus or get_bus()
        remaining = self.budget_remaining()
        rec = bus.emit(
            "slo_burn",
            sink=sink,
            trace_id=trace_id,
            burn_fast=round(burn_fast, 4),
            burn_slow=round(burn_slow, 4),
            threshold=self.threshold,
            budget=round(self.budget, 6),
            fast_window_s=self.fast_window_s,
            slow_window_s=self.slow_window_s,
            error_rate_fast=round(rate_fast, 6),
            error_rate_slow=round(rate_slow, 6),
            good=good,
            bad=bad,
            budget_remaining=(
                round(remaining, 4) if remaining is not None else None
            ),
            reason=reason,
            t=float(now),
        )
        self.last_record = rec
        if capture is not None:
            capture.trigger(
                "slo_burn", trace_id=trace_id, sink=sink,
                arm_profiler=False,
            )
        return rec

    def rearm(self) -> None:
        """Allow the next sustained burn to page again (multi-incident
        harnesses; the capture's own budget is separate)."""
        self.fired = False
