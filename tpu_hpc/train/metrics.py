"""Throughput accounting, matching the reference's in-loop metrics.

Parity: per-batch + per-epoch global/per-device samples-per-second
(multinode_ddp_unet.py:334-397), tokens/s + bubble fraction for PP
(03_pipeline_training.py:280-294), plus MFU accounting (the v4-32
north-star metric, BASELINE.md) which the reference lacks.
"""
from __future__ import annotations

import collections
import contextlib
import dataclasses
import time
from typing import Deque, Dict, Iterator, Optional


@dataclasses.dataclass
class ThroughputMeter:
    """Wall-clock throughput over batches and epochs.

    The reference brackets each batch with cuda.synchronize and
    multiplies by WORLD_SIZE (multinode_ddp_unet.py:334-361); here the
    caller brackets with block_until_ready and items are *global*
    already (jax arrays are process-global), so no world-size fixup.

    Per-batch samples are WINDOWED (bounded deques, newest ``window``
    batches): a meter left running for a million-step run must not
    grow host memory without limit. The Trainer resets per chunk, so
    its summaries never see the bound; a caller that meters more
    batches than ``window`` between summaries gets the newest-window
    aggregate, which is what a rolling throughput reading means.
    """

    n_devices: int = 1
    window: int = 4096
    batch_times: Optional[Deque[float]] = None
    batch_items: Optional[Deque[int]] = None
    _t0: Optional[float] = None

    def __post_init__(self) -> None:
        if self.window < 1:
            raise ValueError(f"window {self.window} must be >= 1")
        if self.batch_times is None:
            self.batch_times = collections.deque(maxlen=self.window)
        if self.batch_items is None:
            self.batch_items = collections.deque(maxlen=self.window)

    def start_batch(self) -> None:
        self._t0 = time.perf_counter()

    def end_batch(self, items: int) -> float:
        assert self._t0 is not None, "start_batch not called"
        dt = time.perf_counter() - self._t0
        self.batch_times.append(dt)
        self.batch_items.append(items)
        self._t0 = None
        return dt

    @property
    def last_throughput(self) -> float:
        """Global items/s for the most recent batch (:351)."""
        return self.batch_items[-1] / self.batch_times[-1]

    def epoch_summary(self, skip_first: int = 1) -> Dict[str, float]:
        """Aggregate items/s over the epoch, skipping warmup batches
        (first batch carries compile time). Parity :363-398."""
        times = list(self.batch_times)[skip_first:] \
            or list(self.batch_times)
        items = list(self.batch_items)[skip_first:] \
            or list(self.batch_items)
        total_t = sum(times)
        total_i = sum(items)
        thpt = total_i / total_t if total_t else 0.0
        return {
            "items_per_s": thpt,
            "items_per_s_per_device": thpt / self.n_devices,
            "mean_batch_s": total_t / max(len(times), 1),
            "total_s": total_t,
            "batches": len(times),
        }

    def reset(self) -> None:
        self.batch_times.clear()
        self.batch_items.clear()


@dataclasses.dataclass
class GoodputMeter:
    """Goodput accounting: productive training wall-clock vs the
    overheads resilience adds back (checkpoint saves, restore-on-
    resume) and everything else (compile, restart tax).

    "Goodput" in the hyperscale-fleet sense (the metric the 100k-GPU
    collective paper's operators optimize): the fraction of a run's
    wall-clock that advanced the model. A preempted-and-resumed run
    reports it per attempt; summing ``productive_s`` across attempts
    against total allocation time gives the fleet view. Buckets:

    * ``productive_s`` -- time inside dispatched training chunks;
    * ``ckpt_s``       -- checkpoint saves (incl. the emergency
                          preemption snapshot) and waits;
    * ``restore_s``    -- checkpoint restore on resume;
    * ``other_s``      -- the remainder (XLA compile, data prep, the
                          restart tax the supervisor's attempt gaps
                          represent).
    """

    productive_s: float = 0.0
    ckpt_s: float = 0.0
    restore_s: float = 0.0
    _t_start: float = dataclasses.field(
        default_factory=time.monotonic
    )

    _KINDS = ("productive", "ckpt", "restore")

    def add(self, kind: str, seconds: float) -> None:
        if kind not in self._KINDS:
            raise ValueError(
                f"unknown goodput bucket {kind!r} (one of {self._KINDS})"
            )
        setattr(self, f"{kind}_s", getattr(self, f"{kind}_s") + seconds)

    @contextlib.contextmanager
    def measure(self, kind: str) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.add(kind, time.perf_counter() - t0)

    def summary(self) -> Dict[str, float]:
        total = time.monotonic() - self._t_start
        accounted = self.productive_s + self.ckpt_s + self.restore_s
        return {
            "total_s": total,
            "productive_s": self.productive_s,
            "ckpt_s": self.ckpt_s,
            "restore_s": self.restore_s,
            "other_s": max(total - accounted, 0.0),
            "goodput": self.productive_s / total if total > 0 else 0.0,
        }


def mfu(
    tokens_per_s: float,
    n_params: int,
    n_devices: int,
    peak_flops_per_device: float,
    attn_flops_per_token: float = 0.0,
    mode: str = "train",
) -> float:
    """Model FLOPs utilization: achieved / peak.

    ``mode="train"`` (the default) uses the standard 6N FLOPs/token
    estimate for dense transformers (fwd 2N + bwd 4N) -- the >=40%
    target metric on the 7B hybrid (BASELINE.md). ``mode="inference"``
    uses the forward-only 2N estimate: a decode step runs no backward,
    so judging serving throughput against 6N would understate its
    utilization 3x (tpu_hpc.serve reports this mode). Optional explicit
    attention FLOPs add on either way.
    """
    factors = {"train": 6.0, "inference": 2.0}
    if mode not in factors:
        raise ValueError(
            f"unknown mfu mode {mode!r} (one of {sorted(factors)})"
        )
    flops_per_token = factors[mode] * n_params + attn_flops_per_token
    achieved = tokens_per_s * flops_per_token
    return achieved / (peak_flops_per_device * n_devices)


def pipeline_bubble_fraction(n_stages: int, n_microbatches: int) -> float:
    """Exact pipeline idle fraction; delegates to the single source of
    truth in parallel/pp.py (the reference reports the (S-1)/M
    approximation instead -- 03_pipeline_training.py:292)."""
    from tpu_hpc.parallel.pp import bubble_fraction

    return bubble_fraction(n_stages, n_microbatches)
