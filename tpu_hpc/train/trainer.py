"""The Trainer: one jit-compiled training step over a sharded state.

Capability parity with the reference's Trainer classes
(multinode_ddp_basic.py:114-208, resnet_fsdp_training.py:104-136) and
their instrumented loops (multinode_ddp_unet.py:327-398): epoch loop,
per-batch throughput, periodic checkpointing, snapshot auto-resume.

TPU-first design: the strategy is not a wrapper around the model but a
pair of sharding plans (params spec tree + batch spec) handed to this
one Trainer. The whole update -- forward, backward, collectives,
optimizer -- is a single jitted function; XLA fuses DDP's all-reduce /
FSDP's all-gather+reduce-scatter into it according to the plan.
"""
from __future__ import annotations

import contextlib
import dataclasses
import math
import os
import time
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from flax import struct
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpu_hpc import obs
from tpu_hpc.config import TrainingConfig
from tpu_hpc.logging_ import get_logger
from tpu_hpc.parallel.fsdp import validate_grad_sync_mode
from tpu_hpc.parallel.plans import derived_pspecs, shardings_for
from tpu_hpc.resilience import guard as guard_lib
from tpu_hpc.resilience.faults import fault_plan_from_env
from tpu_hpc.resilience.guard import GuardPolicy
from tpu_hpc.resilience.heartbeat import (
    ENV_HANG_TIMEOUT,
    HangWatchdog,
    Heartbeat,
    current_attempt,
)
from tpu_hpc.resilience.signals import (
    ENV_ELASTIC_MANAGED,
    PreemptionGuard,
)
from tpu_hpc.train.metrics import GoodputMeter, ThroughputMeter


class TrainState(struct.PyTreeNode):
    """Carried training state. ``model_state`` holds non-trainable
    collections (BatchNorm stats etc.); step enables exact data-stream
    resume (datasets are step-indexed, SURVEY 5.4)."""

    step: jax.Array
    params: Any
    opt_state: Any
    model_state: Any


# forward(params, model_state, batch, step_rng) -> (loss, new_model_state, aux)
ForwardFn = Callable[[Any, Any, Any, jax.Array], Tuple[jax.Array, Any, Dict]]
# eval_forward(params, model_state, batch) -> (loss, aux) -- inference
# mode, no RNG, no state updates (BatchNorm runs on stored stats).
EvalForwardFn = Callable[[Any, Any, Any], Tuple[jax.Array, Dict]]


def _json_finite(x) -> Optional[float]:
    """JSON-safe float: non-finite becomes None. json.dumps would
    otherwise write a bare ``NaN`` token -- Python reads it back, but
    strict-JSON consumers of the run log (jq, BigQuery, JS) reject
    the whole line, and a poisoned step's record is exactly the one
    a dashboard must be able to parse."""
    x = float(x)
    return x if math.isfinite(x) else None


def _leading_spec_extent(mesh: Mesh, spec: P) -> int:
    """Product of mesh-axis sizes sharding a spec's leading dim."""
    if len(spec) == 0 or spec[0] is None:
        return 1
    entry = spec[0]
    names = entry if isinstance(entry, tuple) else (entry,)
    out = 1
    for n in names:
        out *= mesh.shape[n]
    return out


def make_microbatch_constrain(
    mesh: Mesh, batch_sharding: Any
) -> Callable[[Any], Any]:
    """Constraint for a grad-accum microbatched tree [A, B/A, ...]:
    the batch sharding with the accumulation dim replicated. The single
    source for both the Trainer and the fit analyzer, so the step the
    analysis compiles pins microbatches exactly as the training step
    does."""
    micro_sharding = jax.tree.map(
        lambda s: NamedSharding(mesh, P(None, *s.spec)),
        batch_sharding,
        is_leaf=lambda x: isinstance(x, NamedSharding),
    )

    def constrain(tree):
        return jax.tree.map(
            lambda a: jax.lax.with_sharding_constraint(a, micro_sharding),
            tree,
        )

    return constrain


def make_lr_schedule(cfg: TrainingConfig):
    """Scalar or optax schedule from config. ``state.step`` counts
    optimizer updates, so schedules are grad-accum-agnostic and resume
    exactly from a checkpoint (the count rides in the opt state)."""
    total = max(cfg.epochs * cfg.steps_per_epoch, 1)
    if cfg.lr_schedule == "cosine":
        if cfg.warmup_steps >= total:
            raise ValueError(
                f"warmup_steps {cfg.warmup_steps} must be < the run "
                f"length of {total} optimizer updates "
                f"(epochs * steps_per_epoch) for cosine decay"
            )
        return optax.warmup_cosine_decay_schedule(
            init_value=0.0,
            peak_value=cfg.learning_rate,
            warmup_steps=cfg.warmup_steps,
            decay_steps=total,
        )
    if cfg.lr_schedule != "constant":
        raise ValueError(
            f"unknown lr_schedule {cfg.lr_schedule!r}; "
            "expected 'constant' or 'cosine'"
        )
    if cfg.warmup_steps > 0:
        return optax.join_schedules(
            [
                optax.linear_schedule(
                    0.0, cfg.learning_rate, cfg.warmup_steps
                ),
                optax.constant_schedule(cfg.learning_rate),
            ],
            boundaries=[cfg.warmup_steps],
        )
    return cfg.learning_rate


def make_optimizer(cfg: TrainingConfig) -> optax.GradientTransformation:
    """SGD+momentum or AdamW from config (reference optimizers:
    SGD in the DDP/FSDP examples, AdamW with foreach=False in TP --
    tensor_parallel_vit.py:372-378; no foreach quirk exists here),
    with the configured LR schedule. ``adam_moments_dtype="bfloat16"``
    halves AdamW state HBM (mu AND nu; optax keeps the update math in
    fp32 and rounds the stored moments). ``max_grad_norm > 0``
    prepends a global-norm clip: under grad accumulation it sees the
    full accumulated gradient (the clip lives inside the optimizer
    update, after the accumulation scan), so the threshold means the
    same thing at every accum setting."""
    lr = make_lr_schedule(cfg)
    if cfg.weight_decay > 0:
        base = make_adamw(
            lr, cfg.weight_decay, cfg.adam_moments_dtype
        )
    elif cfg.adam_moments_dtype != "float32":
        # The default optimizer is SGD (weight_decay=0); silently
        # ignoring an explicit HBM-halving request would OOM the very
        # run the knob exists for, with no pointer at the cause.
        raise ValueError(
            f"adam_moments_dtype={cfg.adam_moments_dtype!r} has no "
            "effect on the SGD path -- set weight_decay > 0 to get "
            "AdamW, or drop the moments override"
        )
    else:
        base = optax.sgd(lr, momentum=cfg.momentum)
    if cfg.max_grad_norm < 0:
        raise ValueError(
            f"max_grad_norm {cfg.max_grad_norm} must be >= 0 (0 = off)"
        )
    if cfg.max_grad_norm > 0:
        return optax.chain(
            optax.clip_by_global_norm(cfg.max_grad_norm), base
        )
    return base


def make_adamw(
    lr, weight_decay: float, moments_dtype: str = "float32"
) -> optax.GradientTransformation:
    """AdamW with both moments stored in ``moments_dtype``.

    The single construction point shared by the Trainer and the fit
    analyzer (checks/fit.py) -- the fit report certifies the real
    step, so the two must not drift. ``"bfloat16"`` halves
    optimizer-state HBM (the documented unlock for 70B-class models
    on 16 GiB chips, REPORT_70b_128chip_2M.md): optax's ``mu_dtype``
    covers mu, and :func:`_cast_nu` stores nu in bf16 as well. The
    moment *math* stays fp32 -- the stored carries promote against
    the fp32 gradient inside scale_by_adam; only the carry rounds.
    """
    if moments_dtype == "bfloat16":
        return _cast_nu(
            optax.adamw(
                lr, weight_decay=weight_decay, mu_dtype=jnp.bfloat16
            ),
            jnp.bfloat16,
        )
    if moments_dtype != "float32":
        raise ValueError(
            f"adam_moments_dtype {moments_dtype!r} (float32|bfloat16)"
        )
    return optax.adamw(lr, weight_decay=weight_decay)


def _cast_nu(tx: optax.GradientTransformation, dtype):
    """Store the Adam second moment in ``dtype`` across steps.

    Wraps init/update to round ``ScaleByAdamState.nu`` after each
    update; the inner transform's arithmetic runs at its own (fp32)
    precision because the stored nu promotes on first use."""
    is_adam = lambda s: isinstance(s, optax.ScaleByAdamState)  # noqa: E731

    def cast(state):
        return jax.tree.map(
            lambda s: s._replace(
                nu=jax.tree.map(lambda a: a.astype(dtype), s.nu)
            ) if is_adam(s) else s,
            state,
            is_leaf=is_adam,
        )

    def init(params):
        return cast(tx.init(params))

    def update(updates, state, params=None):
        new_updates, new_state = tx.update(updates, state, params)
        return new_updates, cast(new_state)

    return optax.GradientTransformation(init, update)


def make_step_fn(
    forward: ForwardFn,
    optimizer: optax.GradientTransformation,
    seed: int,
    grad_accum: int = 1,
    microbatch_constrain: Optional[Callable[[Any], Any]] = None,
    log_grad_norm: bool = False,
    value_and_grad_fn: Optional[Callable] = None,
    health: bool = False,
    skip_nonfinite: bool = False,
    numeric_fault: Optional[Callable] = None,
) -> Callable[..., Tuple[Any, Dict]]:
    """The training-step body as a free function: forward, backward,
    optimizer update. The Trainer jits this; checks/fit.py AOT-lowers
    the very same function against abstract 7B-scale inputs, so the fit
    analysis certifies the real step, not a lookalike.

    ``grad_accum > 1`` splits the batch into that many microbatches and
    lax.scans the forward/backward, summing gradients and applying ONE
    optimizer update -- same optimizer trajectory as the full batch
    (gradient of the mean = mean of per-microbatch gradients), at
    1/grad_accum of the activation memory. ``state.step`` counts
    optimizer updates, so checkpoints, the data stream, and LR
    schedules are accumulation-agnostic. ``microbatch_constrain``
    re-pins each [A, B/A, ...] microbatched tree to the batch sharding
    (leading dim replicated); without it the reshape leaves microbatch
    rows spread over only a fraction of the data axis.

    ``value_and_grad_fn`` overrides how the (global) loss and gradient
    are computed from ``(params, model_state, batch, rng)`` -- the
    hook the manual comm modes use
    (comm.overlap.make_synced_value_and_grad: per-shard grads inside
    shard_map + explicit bucketed sync). Default None = plain
    ``jax.value_and_grad`` with GSPMD owning the collectives, the
    byte-identical flat path. Under grad accumulation the override
    runs per microbatch (psum is linear: syncing each microbatch's
    gradient and summing equals syncing the sum).

    ``health`` (the numeric-health guard, resilience.guard): the step
    additionally emits a fused health vector into its metrics --
    ``health_loss_finite`` / ``health_grad_norm`` /
    ``health_update_norm`` / ``health_nonfinite`` (leaves with any
    non-finite gradient element) -- computed inside the same jitted
    program, so guard detection rides the metrics the trainer already
    fetches once per chunk. ``skip_nonfinite`` (guard_mode="skip")
    drops the update on-device when the step is poisoned: params,
    opt state and model state keep their pre-step values while
    ``state.step`` still advances (the data stream moves past the bad
    batch), recorded as ``health_skipped``. ``numeric_fault`` is the
    chaos hook (faults.numeric_fault_fn): perturb (loss, grads) as a
    function of the DATA index.

    When either ``health`` or ``numeric_fault`` is armed the returned
    step takes a third argument, ``data_offset`` (a traced scalar:
    the cumulative guard skip-window shift, so
    ``data_index = state.step + data_offset``); otherwise the
    signature -- and the lowered program -- is byte-identical to a
    pre-guard trainer's.
    """
    if value_and_grad_fn is None:
        def value_and_grad_fn(params, ms, batch, rng):
            def loss_fn(p):
                loss, new_ms, aux = forward(p, ms, batch, rng)
                return loss, (new_ms, aux)

            return jax.value_and_grad(loss_fn, has_aux=True)(params)

    tracked = health or numeric_fault is not None

    def step_body(
        state: "TrainState", batch, data_offset
    ) -> Tuple["TrainState", Dict]:
        step_rng = jax.random.fold_in(jax.random.key(seed), state.step)

        if grad_accum == 1:
            (loss, (new_ms, aux)), grads = value_and_grad_fn(
                state.params, state.model_state, batch, step_rng
            )
        else:
            micro = jax.tree.map(
                lambda a: a.reshape(
                    grad_accum, a.shape[0] // grad_accum, *a.shape[1:]
                ),
                batch,
            )
            if microbatch_constrain is not None:
                micro = microbatch_constrain(micro)
            params = state.params

            def body(carry, xs):
                ms, gsum, lsum = carry
                i, mb = xs
                rng = jax.random.fold_in(step_rng, i)
                (loss, (new_ms, aux)), g = value_and_grad_fn(
                    params, ms, mb, rng
                )
                gsum = jax.tree.map(jnp.add, gsum, g)
                return (new_ms, gsum, lsum + loss), aux

            gzero = jax.tree.map(jnp.zeros_like, state.params)
            (new_ms, gsum, lsum), aux_stack = jax.lax.scan(
                body,
                (state.model_state, gzero, jnp.zeros((), jnp.float32)),
                (jnp.arange(grad_accum), micro),
            )
            loss = lsum / grad_accum
            grads = jax.tree.map(lambda g: g / grad_accum, gsum)
            aux = jax.tree.map(lambda a: jnp.mean(a, axis=0), aux_stack)

        if numeric_fault is not None:
            # Chaos injection keyed on the DATA index: after a guard
            # rollback the skip window shifts the stream past the
            # poisoned index, so the relaunch genuinely never re-hits
            # it -- which is exactly what the rollback test proves.
            loss, grads = numeric_fault(
                state.step + data_offset, loss, grads
            )
        updates, new_opt = optimizer.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        new_ms_out = new_ms
        metrics = {"loss": loss, **aux}
        if health:
            # The fused health vector: four scalars riding the
            # stacked chunk metrics the trainer fetches anyway. The
            # norm reductions fuse into the step program like the
            # grad-clip norm does; with clipping on, XLA CSEs the
            # pair.
            loss_finite = jnp.isfinite(loss)
            grad_norm = optax.global_norm(grads)
            update_norm = optax.global_norm(updates)
            nonfinite = sum(
                (
                    jnp.any(~jnp.isfinite(g)).astype(jnp.int32)
                    for g in jax.tree.leaves(grads)
                ),
                jnp.zeros((), jnp.int32),
            )
            metrics["health_loss_finite"] = loss_finite.astype(
                jnp.float32
            )
            metrics["health_grad_norm"] = grad_norm
            metrics["health_update_norm"] = update_norm
            metrics["health_nonfinite"] = nonfinite
            if skip_nonfinite:
                # guard_mode="skip": a poisoned update never touches
                # the carried state -- params, moments AND model
                # state keep their pre-step values -- while step+1
                # still advances the data stream past the bad batch
                # (optax.apply_if_finite's semantics, but fused here
                # so the health vector and the skip share one
                # reduction).
                # update_norm included: finite grads can still
                # overflow the optimizer math (bf16 Adam moments) --
                # a NaN UPDATE poisons params just as surely.
                ok = (
                    loss_finite
                    & (nonfinite == 0)
                    & jnp.isfinite(grad_norm)
                    & jnp.isfinite(update_norm)
                )
                keep = lambda new, old: jax.tree.map(  # noqa: E731
                    lambda n, o: jnp.where(ok, n, o), new, old
                )
                new_params = keep(new_params, state.params)
                new_opt = keep(new_opt, state.opt_state)
                new_ms_out = keep(new_ms_out, state.model_state)
                metrics["health_skipped"] = (~ok).astype(jnp.int32)
        if log_grad_norm:
            if "grad_norm" in metrics:
                # Trace-time guard: silently overwriting a forward's
                # own 'grad_norm' aux would make the metric mean two
                # different things depending on max_grad_norm.
                raise ValueError(
                    "forward() reports an aux metric named "
                    "'grad_norm', which collides with the optimizer-"
                    "level norm logged when max_grad_norm > 0 -- "
                    "rename the aux metric"
                )
            # The PRE-clip norm of the accumulated-mean gradient --
            # the number the clip threshold is judged against. Free
            # when clipping is on: clip_by_global_norm computes the
            # identical reduction and XLA CSEs the pair (which is why
            # the Trainer enables this exactly when max_grad_norm > 0
            # -- unclipped configs keep their pinned collective
            # signatures byte-identical).
            metrics["grad_norm"] = optax.global_norm(grads)
        return (
            TrainState(
                step=state.step + 1,
                params=new_params,
                opt_state=new_opt,
                model_state=new_ms_out,
            ),
            metrics,
        )

    if tracked:
        return step_body

    def step(state: "TrainState", batch) -> Tuple["TrainState", Dict]:
        # Guard off, no numeric fault: the 2-arg signature (and the
        # lowered program) every existing caller -- checks/fit.py's
        # AOT certification, the HLO no-creep pins -- compiled against.
        # data_offset=0 is dead at trace time: nothing reads it.
        return step_body(state, batch, 0)

    return step


class Trainer:
    def __init__(
        self,
        cfg: TrainingConfig,
        mesh: Mesh,
        forward: ForwardFn,
        params: Any,
        model_state: Any = None,
        param_pspecs: Any = None,
        batch_pspec: P = P("data"),
        optimizer: Optional[optax.GradientTransformation] = None,
        checkpoint_manager: Any = None,
        opt_param_pspecs: Any = None,
        eval_forward: Optional[EvalForwardFn] = None,
        comm_plan: Any = None,
    ):
        """``opt_param_pspecs``: optional separate plan for deriving
        optimizer-state shardings (defaults to ``param_pspecs``). This
        is how SHARD_GRAD_OP works: params replicated for compute,
        moments sharded (see fsdp.grad_op_pspecs).

        ``eval_forward``: inference-mode forward for ``evaluate``
        (models with train/eval behavior differences -- BatchNorm,
        dropout -- must supply one, e.g. resnet.make_eval_forward).
        Defaults to the training forward with state updates discarded,
        which is exact for stateless models (llama, vit).

        ``comm_plan``: a pre-resolved planner decision
        (comm.planner.CommDecision) for ``comm_mode="auto"`` --
        callers that had to resolve the decision BEFORE building the
        mesh (bench.py: the mode picks the mesh family) pass it here
        so the trainer runs exactly that decision instead of
        re-planning. Ignored unless cfg.comm_mode == "auto"."""
        self.cfg = cfg
        self.mesh = mesh
        self.forward = forward
        if optimizer is not None and cfg.max_grad_norm > 0:
            # The clip lives inside make_optimizer's chain; silently
            # dropping it here would train unclipped while the
            # grad_norm metric (keyed off cfg) implies otherwise --
            # and silently wrapping could double-clip an optimizer
            # that already chains its own.
            raise ValueError(
                f"max_grad_norm={cfg.max_grad_norm} has no effect on "
                "an explicitly passed optimizer -- chain "
                "optax.clip_by_global_norm into it yourself, or drop "
                "one of the two"
            )
        self.optimizer = optimizer or make_optimizer(cfg)
        self.checkpoint_manager = checkpoint_manager
        self.logger = get_logger()
        # Fault injection is read HERE (not at fit time): the numeric
        # chaos kinds (nan_loss / grad_spike) perturb the jitted step
        # itself, so the plan must exist before the step is built.
        self.fault_plan = fault_plan_from_env()
        if self.fault_plan is not None:
            stage_keys = self.fault_plan.stage_fault_keys()
            if stage_keys:
                # Vacuous-pass guard: the stage-scoped chaos kinds
                # target the MPMD pipeline runtime's per-stage fault
                # domains; on this SPMD Trainer they would never fire
                # and the chaos test would pass by doing nothing.
                raise ValueError(
                    f"TPU_HPC_FAULTS arms stage fault(s) "
                    f"{', '.join(stage_keys)}, but this is an SPMD "
                    "Trainer run -- stage faults are consumed only "
                    "by the MPMD pipeline runtime "
                    "(tpu_hpc.parallel.mpmd / bench.py --workload "
                    "llama-pp --pp-runtime mpmd); refusing to run a "
                    "chaos schedule that cannot inject"
                )
            slice_keys = self.fault_plan.slice_fault_keys()
            if slice_keys and os.environ.get(
                ENV_ELASTIC_MANAGED
            ) != "1":
                # Same vacuous-pass contract for the slice-scoped
                # kinds: a fixed-topology Trainer cannot morph, so a
                # slice fault here would never fire. Under the elastic
                # coordinator (which exports ENV_ELASTIC_MANAGED and
                # consumes the fault itself) the guard stands down.
                raise ValueError(
                    f"TPU_HPC_FAULTS arms slice fault(s) "
                    f"{', '.join(slice_keys)}, but this Trainer is "
                    "not running under the elastic coordinator "
                    "(tpu_hpc.elastic) -- a fixed-topology run "
                    "cannot morph; refusing to run a chaos schedule "
                    "that cannot inject"
                )
        # Numeric-health guard (resilience.guard): None when
        # cfg.guard_mode == "off" -- the step program then stays
        # byte-identical to a pre-guard trainer (HLO no-creep pins).
        self.guard_policy = GuardPolicy.from_config(cfg)
        if (
            self.guard_policy is not None
            and checkpoint_manager is None
            and (
                self.guard_policy.mode == "rollback"
                or self.guard_policy.spike_action == "rollback"
            )
        ):
            # Either rollback trigger (poisoned-step action OR the
            # spike action) needs a snapshot to roll back to; failing
            # here beats an AttributeError at anomaly time.
            raise ValueError(
                "guard_mode='rollback' (or guard_spike_action="
                "'rollback') needs a checkpoint_manager: rollback-to-"
                "last-good restores a snapshot; without one the guard "
                "can only skip or record events"
            )
        numeric_fault = (
            self.fault_plan.numeric_fault_fn()
            if self.fault_plan is not None else None
        )
        # The step signature grows a data_offset arg exactly when the
        # guard or a numeric fault is armed (make_step_fn contract).
        self._guard_tracked = (
            self.guard_policy is not None or numeric_fault is not None
        )
        # Skip windows (persisted guard state): loaded per fit() from
        # the checkpoint dir; empty until a rollback ever happened.
        self._skip_windows: list = []
        self._fit_offset = 0
        self._rolled_back = False
        self.batch_sharding = jax.tree.map(
            lambda s: NamedSharding(mesh, s),
            batch_pspec,
            is_leaf=lambda x: isinstance(x, P),
        )

        if param_pspecs is None:
            param_pspecs = jax.tree.map(lambda _: P(), params)
        self.param_pspecs = param_pspecs

        # Place state on the mesh per plan, via a jitted reshard rather
        # than device_put: the step donates its input state, and
        # device_put can alias the caller's buffers (deleting them out
        # from under the caller on the first donation); jit outputs are
        # always fresh buffers.
        param_shardings = shardings_for(mesh, param_pspecs)
        params = jax.jit(lambda t: t, out_shardings=param_shardings)(params)
        # Optimizer moments shard like the params they mirror; without
        # explicit out_shardings XLA may park them on one device (they
        # have no data dependence on params).
        opt_abstract = jax.eval_shape(self.optimizer.init, params)
        opt_shardings = shardings_for(
            mesh,
            derived_pspecs(
                opt_abstract, params,
                opt_param_pspecs if opt_param_pspecs is not None
                else param_pspecs,
            ),
        )
        opt_state = jax.jit(self.optimizer.init, out_shardings=opt_shardings)(
            params
        )
        model_state = model_state if model_state is not None else {}
        ms_shardings = jax.tree.map(
            lambda _: NamedSharding(mesh, P()), model_state
        )
        if jax.tree.leaves(model_state):
            model_state = jax.jit(lambda t: t, out_shardings=ms_shardings)(
                model_state
            )
        # step is replicated on the mesh (not left uncommitted): restore
        # paths reshard against this template, and a committed
        # single-device scalar would conflict with mesh-wide params.
        self.state = TrainState(
            step=jax.device_put(
                jnp.zeros((), jnp.int32), NamedSharding(mesh, P())
            ),
            params=params,
            opt_state=opt_state,
            model_state=model_state,
        )

        if eval_forward is None:
            if jax.tree.leaves(
                model_state if model_state is not None else {}
            ):
                # Stateful model (BatchNorm etc.): the train-mode
                # forward normalizes by batch statistics, so defaulting
                # to it would report a wrong "inference" metric.
                self.logger.warning(
                    "no eval_forward given for a stateful model; "
                    "evaluate() will run the TRAIN-mode forward "
                    "(batch statistics, not stored stats) -- pass "
                    "eval_forward (e.g. resnet.make_eval_forward) for "
                    "true inference-mode metrics"
                )

            def eval_forward(p, ms, batch):
                loss, _, aux = forward(
                    p, ms, batch, jax.random.key(cfg.seed)
                )
                return loss, aux
        self.eval_forward = eval_forward
        grad_accum = cfg.grad_accum_steps
        if grad_accum < 1:
            raise ValueError(
                f"grad_accum_steps must be >= 1, got {grad_accum}"
            )
        micro_constrain = None
        if grad_accum > 1:
            if cfg.global_batch_size % grad_accum:
                raise ValueError(
                    f"global_batch_size {cfg.global_batch_size} not "
                    f"divisible by grad_accum_steps {grad_accum}"
                )
            # Each microbatch must still cover the whole data axis --
            # an undersized microbatch shards unevenly (GSPMD pads
            # silently) and idles chips every pass, the half-throughput
            # misconfiguration local_batch_size exists to reject.
            micro_bs = cfg.global_batch_size // grad_accum
            data_extent = max(
                (
                    _leading_spec_extent(mesh, s)
                    for s in jax.tree.leaves(
                        batch_pspec,
                        is_leaf=lambda x: isinstance(x, P),
                    )
                ),
                default=1,
            )
            if micro_bs % data_extent:
                raise ValueError(
                    f"microbatch {micro_bs} (global "
                    f"{cfg.global_batch_size} / grad_accum "
                    f"{grad_accum}) not divisible by the batch-sharding "
                    f"extent {data_extent}"
                )
            # Re-pin each microbatched leaf [A, B/A, ...] to the batch
            # sharding with the accumulation dim replicated: the
            # [B] -> [A, B/A] reshape otherwise leaves each microbatch
            # row on a 1/A fraction of the data axis.
            micro_constrain = make_microbatch_constrain(
                mesh, self.batch_sharding
            )

        # Gradient-sync strategy (cfg.comm_mode, the comm-performance
        # layer): flat keeps GSPMD's fused collectives -- the step
        # program is byte-identical to a trainer that predates the
        # knob (pinned by the HLO no-creep test). Manual modes swap in
        # an explicit value_and_grad: per-shard grads inside shard_map
        # + bucketed (optionally two-phase ICI/DCN) reduction.
        # "auto" asks the collective planner (comm/planner.py): the
        # mode and bucket size come from the topology's measured cost
        # table (alpha-beta fallback when none), the decision rides
        # self.comm_plan and is logged as a schema-stamped comm_plan
        # event below. Numerics are unchanged either way -- every
        # candidate the planner may pick is step-identical to flat
        # (the PR-3 parity pins, re-pinned for auto in
        # tests/test_planner.py).
        comm_mode_cfg = getattr(cfg, "comm_mode", "flat")
        self.comm_plan = None
        bucket_bytes = cfg.comm_bucket_mb * 2 ** 20
        if comm_mode_cfg == "auto":
            if comm_plan is not None:
                self.comm_plan = comm_plan
            else:
                from tpu_hpc.comm.planner import (
                    plan_trainer_grad_sync,
                )

                self.comm_plan = plan_trainer_grad_sync(
                    mesh, batch_pspec, self.param_pspecs,
                    self.state.params, bucket_cap_bytes=bucket_bytes,
                )
            comm_mode_cfg = self.comm_plan.mode
            if self.comm_plan.bucket_bytes:
                bucket_bytes = self.comm_plan.bucket_bytes
        comm_mode = validate_grad_sync_mode(
            comm_mode_cfg, self.param_pspecs
        )
        self.comm_mode_resolved = comm_mode
        value_and_grad_fn = None
        if comm_mode != "flat":
            from tpu_hpc.comm import overlap

            value_and_grad_fn = overlap.make_synced_value_and_grad(
                forward, mesh, batch_pspec, self.state.params,
                comm_mode,
                bucket_bytes=bucket_bytes,
            )

        self._step_impl = make_step_fn(
            forward, self.optimizer, cfg.seed,
            grad_accum=grad_accum,
            microbatch_constrain=micro_constrain,
            log_grad_norm=cfg.max_grad_norm > 0,
            value_and_grad_fn=value_and_grad_fn,
            health=self.guard_policy is not None,
            skip_nonfinite=(
                self.guard_policy is not None
                and self.guard_policy.mode == "skip"
            ),
            numeric_fault=numeric_fault,
        )
        # Pin the output state to the planned layout. Without this the
        # compiler may propagate a *different* layout through the update
        # -- concretely, under SHARD_GRAD_OP the new params inherit the
        # sharded moments' layout from optax.apply_updates, silently
        # turning replicated-params into FULL_SHARD after one step.
        self._state_shardings = TrainState(
            step=NamedSharding(mesh, P()),
            params=param_shardings,
            opt_state=opt_shardings,
            model_state=ms_shardings,
        )
        self._train_step = jax.jit(
            self._step_impl,
            donate_argnums=(0,),
            out_shardings=(self._state_shardings, None),
        )
        self._epoch_fns: Dict[Any, Callable] = {}
        self._eval_fns: Dict[Any, Callable] = {}
        self.meter = ThroughputMeter(n_devices=mesh.size)
        self._resumed = False
        # Resilience wiring (tpu_hpc.resilience): goodput accounting
        # always on (zero-cost counters); heartbeat/fault-injection
        # arm themselves from the supervisor's env contract and are
        # no-ops when unsupervised.
        self.goodput = GoodputMeter()
        self.heartbeat = Heartbeat.from_env()
        # (self.fault_plan was read at the top of __init__ -- the
        # numeric chaos kinds are baked into the jitted step.)
        # Checkpoint events (ckpt_fallback / ckpt_integrity) belong in
        # the run log next to the guard verdicts they explain; the
        # manager itself has no sink concept, so the trainer lends it
        # one (host 0 only, like every other run-log write).
        if self.checkpoint_manager is not None and hasattr(
            self.checkpoint_manager, "event_sink"
        ):
            self.checkpoint_manager.event_sink = self._sink()
        # Telemetry spine (tpu_hpc.obs): every record the Trainer
        # writes goes through the process bus -- schema-stamped, into
        # the flight-recorder ring on EVERY host, and to the metrics
        # JSONL on host 0. Flight dumps land next to the checkpoints
        # unless the supervisor already pointed them at its log dir.
        bus = obs.get_bus()
        if bus.flight_dir is None and cfg.checkpoint_dir:
            bus.flight_dir = cfg.checkpoint_dir
        # The planner's comm_mode="auto" verdict, as evidence: which
        # sync strategy this run actually trains under, predicted from
        # which table (or the model) -- next to the epoch records it
        # explains.
        if self.comm_plan is not None:
            self._append_metrics({
                "event": "comm_plan",
                "resolved_from": "auto",
                **self.comm_plan.summary(),
            })
        # Step-time watermark: flags stragglers/stalls (a ``stall``
        # event) and enriches the heartbeat so the supervisor can tell
        # hung from slow without attaching to the process.
        self.stall = obs.StallDetector()
        # HELP once at construction (the ServeMeter.__init__
        # discipline) -- the per-chunk loop must not re-describe
        # under the registry lock.
        reg = obs.get_registry()
        reg.describe("train_steps_total", "Optimizer steps completed")
        reg.describe("train_items_total",
                     "Training items consumed (global batch x steps)")
        reg.describe("train_step", "Current global optimizer step")
        reg.describe("train_step_s",
                     "Per-step wall time within the last chunk (s)")
        # Anomaly-triggered capture (obs/trace.py): a stall-watermark
        # trip or a guard poisoned verdict auto-arms ONE bounded
        # jax.profiler trace + flight dump, keyed by the triggering
        # step's trace id -- symptom to evidence with no operator in
        # the loop. Built per fit() (cfg.capture_on_anomaly), but the
        # knob is validated HERE: a bad capture_steps must fail at
        # construction, not as a mid-fit traceback after bring-up
        # (the guard_mode/manager discipline).
        if cfg.capture_on_anomaly and cfg.capture_steps < 1:
            raise ValueError(
                f"capture_steps {cfg.capture_steps} must be >= 1 "
                "when capture_on_anomaly is set"
            )
        self.capture: Optional[obs.AnomalyCapture] = None
        # Live telemetry plane (obs/digest.py): per-HOST health
        # digests into $TPU_HPC_DIGEST_DIR every chunk boundary, so a
        # fleet rollup (python -m tpu_hpc.obs.live) can compare this
        # host's step watermark against its peers while the run is
        # still going. None (free) unless the env contract arms it.
        self.digest = obs.DigestPublisher.from_env(
            role="host", key=str(jax.process_index())
        )
        # Optional callable(state, step) run when a preemption notice
        # stops the run, BEFORE the emergency snapshot -- the hook for
        # recipe-level cleanup (flush custom logs, export metrics).
        self.on_preempt: Optional[Callable[[Any, int], None]] = None
        # Elastic quiesce hook (tpu_hpc.elastic coordinator):
        # callable(done_step) -> Optional[target_step], polled at
        # every chunk boundary. A target caps the next chunk so the
        # loop lands EXACTLY on it; reaching it stops fit() cleanly
        # with result["quiesced"]=True -- state live, nothing saved,
        # nothing exited -- so the coordinator can morph and resume.
        self.quiesce_check: Optional[
            Callable[[int], Optional[int]]
        ] = None
        self._adopted = False
        self._quiesced = False
        self._watchdog: Optional[HangWatchdog] = None

    def adopt_state(self, state: "TrainState") -> None:
        """Adopt a LIVE state tree (the elastic coordinator's morph
        path). The tree must already lie in this trainer's planned
        shardings -- reshard onto ``self._state_shardings`` first.
        An adopted trainer's fit() trusts the in-memory step over any
        disk checkpoint: a morph never wrote a snapshot, so the newest
        checkpoint predates the transition and resuming from it would
        silently re-train the morphed span."""
        self.state = state
        self._adopted = True

    # -- the HOT LOOP body lives in make_step_fn (SURVEY 3.1/3.4);
    # self._step_impl is bound in __init__ --

    def _get_epoch_fn(self, dataset, n_steps: int) -> Callable:
        """Jit (and cache) ``n_steps`` training steps as one ``lax.scan``,
        generating batches on-device from the dataset's traceable
        generator.

        One dispatch per chunk instead of (datagen + device_put + step)
        per batch: on remote/async transports per-dispatch latency
        otherwise dominates (each host->device round trip costs more
        than the step itself). This is the "minimise host<->device
        transfers" rule applied to the whole hot loop.

        ``state.step`` is the single source of truth for the data/RNG
        index inside the scan, so the stream stays aligned across
        resume regardless of where the checkpoint landed.
        """
        # Datasets are frozen dataclasses, so hash by value: an
        # id()-keyed cache could silently reuse a stale jitted epoch fn
        # after the id is recycled by the allocator. Unhashable datasets
        # fall back to identity keys, with the dataset pinned in the
        # cache entry so its id cannot be recycled while the entry lives.
        key = self._dataset_key(dataset, n_steps)
        if key in self._epoch_fns:
            return self._epoch_fns[key][0]
        gen = dataset.traced_batch
        bs = self.cfg.global_batch_size
        batch_sharding = self.batch_sharding

        if self._guard_tracked:
            # Guard/chaos-armed trainers thread the skip-window offset
            # through the chunk as a TRACED scalar: data and fault
            # indices become step+offset, and a post-rollback offset
            # change re-dispatches the SAME compiled chunk -- the
            # guard must not cost a recompile per rollback (nor any
            # in steady state: same program, one extra scalar input).
            def epoch_fn(state: TrainState, data_offset):
                def body(st, _):
                    batch = gen(st.step + data_offset, bs)
                    batch = jax.tree.map(
                        lambda a: jax.lax.with_sharding_constraint(
                            a, batch_sharding
                        ),
                        batch,
                    )
                    return self._step_impl(st, batch, data_offset)

                return jax.lax.scan(body, state, None, length=n_steps)

            lower_args = (
                self.state,
                jax.ShapeDtypeStruct(
                    (), jnp.int32,
                    sharding=NamedSharding(self.mesh, P()),
                ),
            )
        else:
            def epoch_fn(state: TrainState):
                def body(st, _):
                    batch = gen(st.step, bs)
                    batch = jax.tree.map(
                        lambda a: jax.lax.with_sharding_constraint(
                            a, batch_sharding
                        ),
                        batch,
                    )
                    return self._step_impl(st, batch)

                return jax.lax.scan(body, state, None, length=n_steps)

            lower_args = (self.state,)

        fn = jax.jit(
            epoch_fn,
            donate_argnums=(0,),
            out_shardings=(self._state_shardings, None),
        )
        # AOT-compile now, outside the caller's timing window: epoch-0
        # throughput previously included XLA compilation (VERDICT r1
        # metering note), forcing benches to discard the whole first
        # epoch. The compiled executable is what gets cached.
        fn = fn.lower(*lower_args).compile()
        self._epoch_fns[key] = (fn, dataset)
        return fn

    def _offset_arg(self, off: int):
        """The chunk's skip-window offset as a mesh-replicated traced
        scalar -- a changed value re-dispatches the same compiled
        program (a baked Python int would recompile per rollback)."""
        return jax.device_put(
            jnp.int32(off), NamedSharding(self.mesh, P())
        )

    def train_step(self, batch) -> Dict:
        batch = jax.tree.map(
            lambda a: jax.device_put(a, self.batch_sharding), batch
        )
        if self._guard_tracked:
            self.state, metrics = self._train_step(
                self.state, batch, self._offset_arg(self._fit_offset)
            )
        else:
            self.state, metrics = self._train_step(self.state, batch)
        return metrics

    def _dataset_key(self, dataset, *extra):
        try:
            key = (dataset, *extra)
            hash(key)
            return key
        except TypeError:
            return ((type(dataset).__name__, id(dataset)), *extra)

    def eval_step(self, batch) -> Dict:
        """One jitted inference-mode step (no grads, no state updates)."""
        batch = jax.tree.map(
            lambda a: jax.device_put(a, self.batch_sharding), batch
        )
        if "step" not in self._eval_fns:
            def one(state, b):
                loss, aux = self.eval_forward(
                    state.params, state.model_state, b
                )
                return {"loss": loss, **aux}

            self._eval_fns["step"] = (jax.jit(one), None)
        return self._eval_fns["step"][0](self.state, batch)

    def evaluate(self, dataset, n_steps: Optional[int] = None) -> Dict:
        """Jitted evaluation pass: mean loss (and any aux metrics, e.g.
        accuracy) over ``n_steps`` batches, sharded exactly like
        training.

        Parity: the reference's ``Trainer.test()`` accuracy loop
        (resnet_fsdp_training.py:138-155) and the UNet test-loss pass
        (multinode_fsdp_unet.py) -- under torch each rank loops and
        all-reduces correct-counts; here the whole pass is one scanned
        jit dispatch and the mesh handles the reduction.
        """
        n_steps = n_steps or self.cfg.steps_per_epoch
        bs = self.cfg.global_batch_size
        if hasattr(dataset, "traced_batch"):
            key = self._dataset_key(dataset, n_steps, "eval")
            if key not in self._eval_fns:
                gen = dataset.traced_batch
                batch_sharding = self.batch_sharding
                eval_forward = self.eval_forward

                def eval_fn(state: TrainState):
                    def body(_, i):
                        batch = gen(i, bs)
                        batch = jax.tree.map(
                            lambda a: jax.lax.with_sharding_constraint(
                                a, batch_sharding
                            ),
                            batch,
                        )
                        loss, aux = eval_forward(
                            state.params, state.model_state, batch
                        )
                        return None, {"loss": loss, **aux}

                    _, per_step = jax.lax.scan(
                        body, None, jnp.arange(n_steps)
                    )
                    return jax.tree.map(
                        lambda a: jnp.mean(a, axis=0), per_step
                    )

                self._eval_fns[key] = (jax.jit(eval_fn), dataset)
            metrics = self._eval_fns[key][0](self.state)
        else:
            # Accumulate on-device; one host sync at the end (the
            # module's minimise-host<->device-transfers rule).
            sums: Dict[str, jax.Array] = {}
            for i in range(n_steps):
                m = self.eval_step(dataset.batch_at(i, bs))
                for k, v in m.items():
                    sums[k] = sums[k] + v if k in sums else v
            metrics = {
                k: v / n_steps
                for k, v in jax.device_get(sums).items()
            }
        out = {
            k: float(jax.device_get(v)) for k, v in metrics.items()
        }
        if jax.process_index() == 0:
            self.logger.info(
                "eval | %s",
                " | ".join(f"{k} {v:.5f}" for k, v in sorted(out.items())),
            )
            # Reserved schema fields win over user metric names: an
            # eval aux named 'step'/'time' must not clobber the
            # record's position/timestamp for every consumer.
            self._append_metrics({
                **out,
                "event": "eval",
                "time": time.time(),
                "step": int(jax.device_get(self.state.step)),
                "n_steps": n_steps,
            })
        return out

    def _sink(self) -> Optional[str]:
        """The metrics JSONL path, on the host that owns the run log
        (host 0); None elsewhere, so bus emits ring-buffer only."""
        if self.cfg.metrics_path and jax.process_index() == 0:
            return self.cfg.metrics_path
        return None

    def _append_metrics(self, record: Dict) -> None:
        """Host-0 append-only JSONL run log (``cfg.metrics_path``) --
        the reference's benchmark_results.log discipline
        (scripts/main.py:381-397) as structured records, routed
        through the obs bus: schema-stamped (run_id/host/pid), held in
        the flight-recorder ring, and appended to the file when one is
        configured."""
        obs.get_bus().emit_record(record, sink=self._sink())

    def _emit_span(self, name: str, dur_s: float, step: int,
                   **fields) -> None:
        """One pre-measured phase duration as a ``span`` event (+ a
        registry histogram) -- the report's step-time breakdown reads
        these."""
        obs.emit_span(
            name, dur_s, sink=self._sink(), step=step,
            hist=f"train_{name}_s", **fields,
        )

    def _snapshot_config(self) -> None:
        """Write config.yaml next to the checkpoints -- the exact
        hyperparameters that produced them. Called at save time (a run
        that never saved cannot relabel another run's shards), AFTER
        the async save commits (wait()): a crash while the first save
        is still in flight must not leave this run's label on a
        previous run's shards. Once per fit -- the config cannot
        change mid-run, so later saves keep their async overlap."""
        if getattr(self, "_config_snapshotted", False):
            return
        ckpt_dir = getattr(self.checkpoint_manager, "directory", None)
        if ckpt_dir is None:
            return
        self.checkpoint_manager.wait()
        self._config_snapshotted = True
        if jax.process_index() != 0:
            return
        cfg = getattr(self, "_effective_cfg", self.cfg)
        cfg.to_yaml(os.path.join(ckpt_dir, "config.yaml"))

    def maybe_resume(self) -> int:
        """Snapshot auto-resume: continue from the stored step if a
        checkpoint exists (parity: multinode_ddp_basic.py:144-155)."""
        if self.checkpoint_manager is None or not self.cfg.resume:
            return 0
        with self.goodput.measure("restore"), obs.span(
            "restore", sink=self._sink(), hist="train_restore_s"
        ):
            restored = self.checkpoint_manager.restore_latest(
                self.state,
                max_inflight_bytes=(
                    self.cfg.reshard_max_inflight_mb * (1 << 20)
                    if getattr(self.cfg, "reshard_max_inflight_mb", 0)
                    else None
                ),
            )
        if restored is not None:
            self.state = restored
            step = int(jax.device_get(self.state.step))
            self.logger.info("resumed from checkpoint at step %d", step)
            info = getattr(
                self.checkpoint_manager, "last_restore_info", None
            )
            if info and info.get("elastic"):
                # The cross-topology path ran: this relaunch resumed
                # onto a DIFFERENT mesh shape via tpu_hpc.reshard.
                # Record it in the run log so the goodput report and
                # the elastic-resume test can see which restarts were
                # elastic and what the move cost.
                self.logger.info(
                    "elastic resume: checkpoint mesh %s -> live mesh "
                    "%s", info.get("src_mesh"), info.get("tgt_mesh"),
                )
                self._append_metrics({
                    "event": "elastic_restore",
                    "from_step": step,
                    "src_mesh": info.get("src_mesh"),
                    "tgt_mesh": info.get("tgt_mesh"),
                    "plan": info.get("plan"),
                })
            return step
        return 0

    def fit(
        self, dataset, epochs: Optional[int] = None,
        eval_dataset=None, eval_steps: Optional[int] = None,
    ) -> Dict:
        """Epoch loop with throughput instrumentation.

        Output format parity: per-batch global items/s, per-epoch and
        run summaries incl. per-device rate (multinode_ddp_unet.py:
        334-398). Dataset contract: ``batch_at(step, global_batch)``.

        ``eval_dataset``: run :meth:`evaluate` on it after every
        epoch (``eval_steps`` batches; default a full
        ``steps_per_epoch``) -- each pass logs and appends an
        ``event: eval`` record to the metrics JSONL, giving a train
        AND eval loss curve from one fit call (the convergence-run
        evidence format).
        """
        cfg = self.cfg
        epochs = epochs or cfg.epochs
        if epochs != cfg.epochs and cfg.lr_schedule == "cosine":
            # The cosine schedule was sized from cfg.epochs at optimizer
            # construction; a longer override would silently flatline at
            # the end value and a shorter one never completes decay.
            raise ValueError(
                f"fit(epochs={epochs}) conflicts with lr_schedule="
                f"'cosine' sized for cfg.epochs={cfg.epochs}: set "
                "cfg.epochs to the intended run length instead"
            )
        # Per-fit accounting: the goodput record is an attempt-scoped
        # trail; carrying buckets (or the wall-clock origin) across
        # fits would misreport every fit after the first.
        self.goodput = GoodputMeter()
        self._rolled_back = False
        self._fit_offset = 0
        self._skip_windows = []
        if self.guard_policy is not None:
            # Persisted guard state: skip windows from earlier
            # rollbacks (this process's or a previous attempt's) keep
            # fast-forwarding the stream past poisoned batches.
            self._skip_windows = guard_lib.load_state(
                self._guard_dir()
            )["skip_windows"]
        self._quiesced = False
        if self._adopted:
            # Live morphed state (adopt_state): the in-memory step IS
            # the data-stream truth. Disk holds only pre-morph
            # snapshots -- restoring one would rewind past the morph.
            start_step = int(jax.device_get(self.state.step))
        else:
            start_step = self.maybe_resume()
        # Preemption safety: TPU-VM spot/maintenance events deliver
        # SIGTERM with a short grace window. Snapshot-then-exit is the
        # recovery model (the reference's PBS-resubmission + snapshot
        # pattern, SURVEY 5.3): the relaunched job auto-resumes from
        # the saved step. Installed only around fit() and only when a
        # checkpoint manager exists; chunk boundaries check the flag
        # (PreemptionGuard handles the non-main-thread and
        # restore-previous-disposition edge cases).
        # (Guard install and watchdog start are deferred to just
        # before the try/finally below: an exception in the remaining
        # setup -- metrics I/O, profiler construction -- must not
        # leak a signal handler or leave an un-ticked watchdog to
        # os._exit the process while the real error propagates.)
        steps_per_epoch = cfg.steps_per_epoch
        total_steps = epochs * steps_per_epoch
        run_summaries = []
        last_metrics: Dict = {}
        # The EFFECTIVE run shape: a fit(epochs=) override must be
        # what the reproducibility records say, or re-running from
        # them trains a different length. Snapshotted next to the
        # checkpoints at SAVE time (not here): a run that dies before
        # its first save must not relabel shards an earlier run left
        # in the same directory.
        self._effective_cfg = dataclasses.replace(cfg, epochs=epochs)
        self._config_snapshotted = False  # per-fit: epochs may differ
        # Emitted on EVERY host (the file write still lands only on
        # host 0 via _sink), and even without a metrics_path: a
        # flight dump from whichever host wedges must carry the run's
        # identity and shape -- the wedging host is rarely the one
        # writing the run log.
        dev = jax.devices()[0]
        self._append_metrics({
            "event": "run_start",
            "time": time.time(),
            "start_step": start_step,
            "total_steps": total_steps,
            "n_devices": jax.device_count(),
            "n_processes": jax.process_count(),
            "device_kind": getattr(
                dev, "device_kind", dev.platform
            ),
            "jax_version": jax.__version__,
            "config": dataclasses.asdict(self._effective_cfg),
        })
        # Fast path: datasets with a traceable generator get whole-epoch
        # lax.scan (one dispatch/epoch); host-fed datasets fall back to
        # the per-step loop. A resume landing mid-epoch runs a shorter
        # first chunk so checkpoint cadence stays epoch-aligned.
        scanned = hasattr(dataset, "traced_batch")
        prof = None
        if cfg.profile:
            from tpu_hpc.profiling import TrainingProfiler

            prof = TrainingProfiler(
                cfg.profile_dir, cfg.profile_start_step,
                cfg.profile_num_steps,
            )
        done = start_step
        if cfg.capture_on_anomaly:
            self.capture = obs.AnomalyCapture(
                profile_dir=os.path.join(
                    cfg.checkpoint_dir or cfg.profile_dir, "anomaly"
                ),
                n_steps=cfg.capture_steps,
            )
        guard: Optional[PreemptionGuard] = None
        if self.checkpoint_manager is not None:
            guard = PreemptionGuard().install()
        # Hang watchdog (supervisor env contract): a train_step or
        # collective that stalls past the timeout aborts the process
        # with stack dumps + EXIT_HANG instead of hanging the
        # allocation. The timeout must cover one epoch chunk plus one
        # XLA compile -- ticks happen at chunk boundaries. Started
        # immediately before the try so the finally below is the only
        # exit path with it running.
        hang_timeout = float(
            os.environ.get(ENV_HANG_TIMEOUT, "0") or 0
        )
        if hang_timeout > 0:
            self._watchdog = HangWatchdog(
                hang_timeout,
                dump_path=os.path.join(
                    self.cfg.checkpoint_dir or ".",
                    f"hang.attempt{current_attempt()}.dump",
                ),
            ).start()
        try:
            last_metrics = self._fit_loop(
                dataset, done, total_steps, steps_per_epoch, scanned,
                prof, guard, run_summaries,
                eval_dataset=eval_dataset, eval_steps=eval_steps,
            )
        finally:
            # Always restore the SIGTERM disposition -- a dataset/OOM
            # exception mid-loop must not leave the no-op flag handler
            # installed for the life of the process (a later real
            # SIGTERM would then neither snapshot nor exit).
            if guard is not None:
                guard.restore()
            if self._watchdog is not None:
                self._watchdog.stop()
                self._watchdog = None
            if prof is not None:
                prof.stop()
            if self.capture is not None:
                # A capture window still open at teardown must not
                # leak its jax.profiler trace.
                self.capture.close()
        preempted = guard is not None and guard.triggered
        goodput = self.goodput.summary()
        end_step = int(jax.device_get(self.state.step))
        if jax.process_index() == 0:
            # Restart accounting: every fit appends one goodput record
            # so a supervised, preempted-and-resumed run leaves an
            # auditable productive-vs-overhead trail per attempt.
            self._append_metrics({
                "event": "run_end",
                "time": time.time(),
                "step": end_step,
                "preempted": preempted,
                "rolled_back": self._rolled_back,
                "attempt": current_attempt(),
                "resumed_from_step": start_step,
                "goodput": goodput,
            })
        # Close the run JSONL with the final counter/gauge/histogram
        # state -- ONE metrics namespace shared with serving, exported
        # the same two ways (JSONL snapshot + Prometheus textfile).
        reg = obs.get_registry()
        reg.emit_snapshot(sink=self._sink(), step=end_step)
        reg.write_prometheus()
        return {
            "epochs": run_summaries,
            "final_loss": float(jax.device_get(last_metrics["loss"]))
            if last_metrics
            else None,
            "preempted": preempted,
            "rolled_back": self._rolled_back,
            "quiesced": self._quiesced,
            "goodput": goodput,
        }

    def _fit_loop(
        self, dataset, done, total_steps, steps_per_epoch, scanned,
        prof, guard, run_summaries,
        eval_dataset=None, eval_steps=None,
    ):
        cfg = self.cfg
        last_metrics: Dict = {}
        while done < total_steps:
            if self._watchdog is not None:
                self._watchdog.tick()
            # Elastic quiesce: the coordinator's hook names the step
            # boundary it wants the run stopped at. Reaching it stops
            # the loop with everything live (no save, no exit); a
            # future target caps the chunk so the loop lands exactly
            # on it instead of overshooting into the next epoch.
            quiesce_at = None
            if self.quiesce_check is not None:
                quiesce_at = self.quiesce_check(done)
                if quiesce_at is not None and quiesce_at <= done:
                    self._quiesced = True
                    break
            epoch = done // steps_per_epoch
            chunk = min(steps_per_epoch - done % steps_per_epoch,
                        total_steps - done)
            if quiesce_at is not None:
                chunk = min(chunk, quiesce_at - done)
            # Guard skip windows: the data offset is constant within
            # one dispatched chunk (it rides in as one traced scalar),
            # so a chunk must never span a window boundary -- cap it
            # at the next offset change. Steps before the boundary
            # replay their original batches exactly; steps at/after it
            # fast-forward past the poisoned span.
            off = 0
            if self._guard_tracked and self._skip_windows:
                off = guard_lib.offset_at(self._skip_windows, done)
                nxt = guard_lib.next_boundary(self._skip_windows, done)
                if nxt is not None:
                    chunk = min(chunk, nxt - done)
            self._fit_offset = off
            # Steps are dispatched async and pipelined on-device; the
            # chunk is timed between two host fetches (a fetch forces
            # completion of everything dispatched before it). Per-batch
            # block_until_ready bracketing -- the reference's
            # cuda.synchronize pattern -- both breaks pipelining and
            # under-reports on asynchronous transports. Per-batch
            # variance is invisible by design on this path (one
            # dispatch per chunk); the host-fed fallback below still
            # meters per batch. Compilation happens inside
            # _get_epoch_fn (AOT), before the clock starts.
            if scanned:
                epoch_fn = self._get_epoch_fn(dataset, chunk)
            jax.device_get(self.state.step)  # drain pending work
            if self._watchdog is not None:
                # Compile time (AOT, above) must not eat into the
                # chunk's stall budget.
                self._watchdog.tick()
            if prof is not None:
                # Chunked loops advance a whole epoch per dispatch, so
                # the window opens/closes at chunk boundaries.
                prof.step(done)
            self.meter.reset()
            self.meter.start_batch()
            # Step-boundary marker for XProf per-step breakdowns; the
            # whole chunk is one dispatch, so one annotation per chunk.
            ann = (
                prof.annotate(done) if prof is not None
                else contextlib.nullcontext()
            )
            data_s = 0.0
            health_chunk = None
            with self.goodput.measure("productive"), ann:
                if scanned:
                    if self._guard_tracked:
                        self.state, stacked = epoch_fn(
                            self.state, self._offset_arg(off)
                        )
                    else:
                        self.state, stacked = epoch_fn(self.state)
                    last_metrics = jax.tree.map(lambda a: a[-1], stacked)
                    if self.guard_policy is not None:
                        # The guard's per-step evidence: the stacked
                        # health vectors for the WHOLE chunk (a few
                        # scalars per step), fetched in the same
                        # device_get as the loss below.
                        health_chunk = {
                            k: stacked[k]
                            for k in guard_lib.HEALTH_KEYS
                            if k in stacked
                        }
                else:
                    per_step_health = []
                    for i in range(chunk):
                        t_data = time.perf_counter()
                        batch = dataset.batch_at(
                            done + i + off, cfg.global_batch_size
                        )
                        data_s += time.perf_counter() - t_data
                        last_metrics = self.train_step(batch)
                        if self.guard_policy is not None:
                            per_step_health.append({
                                k: last_metrics[k]
                                for k in guard_lib.HEALTH_KEYS
                                if k in last_metrics
                            })
                    if self.guard_policy is not None and per_step_health:
                        health_chunk = {
                            k: [row[k] for row in per_step_health]
                            for k in per_step_health[0]
                        }
                # Injected straggler delay (chaos matrix): INSIDE the
                # metered window, so the slowness is visible to the
                # stall watermark exactly like a degraded host's.
                if self.fault_plan is not None:
                    self.fault_plan.maybe_straggle(done + chunk)
                # ONE host fetch per chunk, INSIDE the productive
                # window: it is both the chunk barrier (the dispatched
                # work isn't done until the fetch lands) and the
                # source for the log line, the JSONL record AND the
                # guard classification below -- fetching loss for the
                # barrier, loss again for the log, and the health
                # vectors separately would cost three device round
                # trips per chunk.
                last_metrics, health_chunk = jax.device_get(
                    (last_metrics, health_chunk)
                )
            chunk_s = self.meter.end_batch(chunk * cfg.global_batch_size)
            done += chunk
            s_per_step = chunk_s / max(chunk, 1)
            # The chunk's trace id (obs/trace.py): every phase span,
            # stall verdict and checkpoint bracket of this chunk
            # carries it, so the critical-path analyzer can decompose
            # per-step time and a capture correlates to the step that
            # tripped it. Run_id-scoped, so multi-host flight rings
            # merge on the same ids.
            tid = obs.step_trace_id(done)
            # Phase spans (the report's step-time breakdown). On the
            # scanned path data generation and the grad collectives
            # are fused into the one compiled chunk, so the whole
            # chunk is "compute" -- the report names the fusion
            # rather than silently omitting those phases; the
            # host-fed path meters its host data time separately.
            self._emit_span(
                "compute", max(chunk_s - data_s, 0.0), done, n=chunk,
                trace_id=tid,
            )
            if data_s > 0:
                self._emit_span(
                    "data", data_s, done, n=chunk, trace_id=tid
                )
            # Straggler/stall watermark: a breach emits a ``stall``
            # event (every host -- the straggling host is rarely the
            # one writing the run log).
            stall_info = self.stall.observe(
                done, s_per_step, sink=self._sink(), trace_id=tid
            )
            if stall_info is not None and self.capture is not None:
                # Stall -> evidence: one bounded profiler capture +
                # flight dump keyed by this chunk's trace id.
                self.capture.trigger(
                    "stall", trace_id=tid, step=done,
                    sink=self._sink(),
                )
            if self.capture is not None:
                self.capture.step(done)
            reg = obs.get_registry()
            reg.inc("train_steps_total", chunk)
            reg.inc("train_items_total", chunk * cfg.global_batch_size)
            reg.set_gauge("train_step", done)
            reg.observe("train_step_s", s_per_step)
            if self._watchdog is not None:
                self._watchdog.tick()
            if self.heartbeat is not None:
                # last-step + step-time enrichment: an outside reader
                # (the supervisor, an operator's cat) can now tell
                # "wedged" from "slower than its own recent past".
                self.heartbeat.tick(done, **self.stall.heartbeat_extra())
            if self.digest is not None:
                # The digest twin of the heartbeat enrichment: the
                # registry's counters/gauges + mergeable sketches and
                # the SAME normalized (step_s, watermark_s) signal,
                # published onto this host's channel for the fleet
                # rollup's cross-host straggler comparison.
                self.digest.publish_registry(
                    step=done, **self.stall.digest_extra()
                )
            summary = self.meter.epoch_summary(skip_first=0)
            run_summaries.append(summary)
            if jax.process_index() == 0:
                loss = float(last_metrics["loss"])
                self.logger.info(
                    "epoch %d | loss %.5f | %.1f items/s global | "
                    "%.1f items/s/device | %.3fs/step",
                    epoch, loss,
                    summary["items_per_s"],
                    summary["items_per_s_per_device"],
                    summary["total_s"] / max(chunk, 1),
                )
                rec = {
                    "event": "epoch",
                    "time": time.time(),
                    "epoch": epoch,
                    "step": done,
                    # A guarded run can legitimately log a poisoned
                    # chunk's NaN loss -- null, not a bare NaN token.
                    "loss": _json_finite(loss),
                    "items_per_s": summary["items_per_s"],
                    "items_per_s_per_device":
                        summary["items_per_s_per_device"],
                    "s_per_step": summary["total_s"] / max(chunk, 1),
                }
                if "grad_norm" in last_metrics:
                    rec["grad_norm"] = _json_finite(
                        last_metrics["grad_norm"]
                    )
                self._append_metrics(rec)
                reg.set_gauge("train_loss", loss)
                reg.set_gauge(
                    "train_items_per_s", summary["items_per_s"]
                )
            # Prometheus textfile exposition: a no-op unless
            # $TPU_HPC_PROM_FILE names the scrape file.
            reg.write_prometheus()
            # Numeric-health guard: classify every step of the chunk
            # (host-side, against the rolling healthy-norm median)
            # BEFORE the periodic save below -- a poisoned state must
            # never become the newest snapshot. On rollback the loop
            # stops here: quarantine + skip window are durable, the
            # process exits EXIT_ROLLBACK, and the relaunch resumes
            # from the last-good checkpoint.
            if self.guard_policy is not None and health_chunk:
                if self._guard_check(done - chunk, chunk,
                                     health_chunk, off):
                    break
            # Fault injection (no-op unless TPU_HPC_FAULTS is set):
            # fires BEFORE the periodic save so a kill at step N
            # leaves the previous checkpoint as the newest one -- the
            # restart really re-trains the killed span.
            if self.fault_plan is not None:
                self.fault_plan.on_step(done)
            if eval_dataset is not None:
                # evaluate() logs and appends its own 'eval' metrics
                # record (host 0); runs on every host so any sharded
                # collectives inside the eval step stay collective.
                self.evaluate(eval_dataset, n_steps=eval_steps)
            if (
                self.checkpoint_manager is not None
                and cfg.save_every
                and done % (cfg.save_every * steps_per_epoch) == 0
            ):
                with self.goodput.measure("ckpt"), obs.span(
                    "ckpt", sink=self._sink(), step=done,
                    hist="train_ckpt_s", trace_id=tid,
                ):
                    self.checkpoint_manager.save(self.state)
                    self._snapshot_config()
            if guard is not None and guard.triggered:
                self.logger.warning(
                    "preemption notice (SIGTERM): snapshotting at "
                    "step %d and stopping -- exit with "
                    "resilience.EXIT_RESUMABLE; the relaunch "
                    "auto-resumes with --resume",
                    done,
                )
                if self.on_preempt is not None:
                    self.on_preempt(self.state, done)
                # Flight evidence FIRST: the ring holds the events
                # leading up to the notice, and the grace window may
                # not survive the emergency save below.
                obs.dump_flight("preempt")
                with self.goodput.measure("ckpt"), obs.span(
                    "ckpt", sink=self._sink(), step=done,
                    hist="train_ckpt_s", trace_id=tid,
                ):
                    if done not in (
                        self.checkpoint_manager.all_steps() or []
                    ):
                        # Emergency synchronous save: the grace window
                        # may be seconds; save_now blocks until the
                        # snapshot is durable.
                        self.checkpoint_manager.save_now(self.state)
                    self._snapshot_config()
                    self.checkpoint_manager.wait()
                break
        return last_metrics

    # -- numeric-health guard (resilience.guard) ----------------------
    def _guard_dir(self) -> Optional[str]:
        """Where guard state (skip windows) persists: next to the
        checkpoints it rolls back to."""
        return (
            getattr(self.checkpoint_manager, "directory", None)
            or self.cfg.checkpoint_dir
        )

    def _guard_check(
        self, chunk_start: int, chunk: int, health_chunk, offset: int
    ) -> bool:
        """Classify the chunk's per-step health vectors; emit
        guard_verdict events and counters; on a verdict the policy
        wants rolled back, execute the rollback and return True (the
        fit loop stops)."""
        policy = self.guard_policy
        reg = obs.get_registry()
        rows = guard_lib.health_rows(health_chunk, chunk)
        last_bad = rollback_at = None
        for i, row in enumerate(rows):
            step = chunk_start + i
            verdict = policy.classify(step, row)
            if verdict.skipped:
                reg.inc("guard_skipped_total")
            if verdict.healthy:
                continue
            reg.inc(f"guard_{verdict.verdict}_total")
            wants = policy.wants_rollback(verdict)
            rec = {
                "event": "guard_verdict",
                "step": step,
                # The verdict joins the step's causal trace -- a
                # guard-triggered capture is keyed by this exact id,
                # so the symptom record and the evidence bundle grep
                # to each other.
                "trace_id": obs.step_trace_id(step),
                "verdict": verdict.verdict,
                "action": (
                    "rollback" if wants
                    else "skip" if verdict.skipped else "event"
                ),
                "grad_norm": _json_finite(verdict.grad_norm),
                "update_norm": _json_finite(verdict.update_norm),
                "loss_finite": verdict.loss_finite,
                "nonfinite": verdict.nonfinite,
                "data_index": step + offset,
            }
            if verdict.watermark is not None:
                rec["watermark"] = verdict.watermark
            if verdict.ratio is not None:
                rec["ratio"] = verdict.ratio
            self._append_metrics(rec)
            self.logger.warning(
                "guard: step %d classified %s (grad_norm %s, "
                "nonfinite leaves %d) -- action %s",
                step, verdict.verdict, verdict.grad_norm,
                verdict.nonfinite, rec["action"],
            )
            if (
                self.capture is not None
                and verdict.verdict == "poisoned"
            ):
                # Poisoned step -> evidence bundle keyed by the
                # poisoned step's trace id (the rollback below also
                # dumps the ring; the capture's bundle additionally
                # carries the HBM state and, when the run continues
                # in skip mode, a bounded profiler window).
                self.capture.trigger(
                    "guard_poisoned",
                    trace_id=obs.step_trace_id(step),
                    step=step, sink=self._sink(),
                )
            # The rollback window anchors at the first verdict that
            # DEMANDS rollback -- an earlier event-only spike in the
            # same chunk was, by configured policy, fine to train
            # through; rolling its (healthy-by-policy) span back and
            # skipping its data would override that choice.
            if rollback_at is None and wants:
                rollback_at = step
            if rollback_at is not None:
                last_bad = step
        if rollback_at is None:
            return False
        self._guard_rollback(rollback_at, last_bad, offset)
        return True

    def _guard_rollback(
        self, first_bad: int, last_bad: int, offset: int
    ) -> None:
        """Rollback-to-last-good: quarantine any snapshot that
        contains the anomaly, persist the skip window over the
        poisoned data indices, and mark the fit rolled-back -- the
        entry point then exits EXIT_ROLLBACK and the supervisor
        relaunches from the last-good checkpoint (through the
        ordinary restore path, elastic reshard included)."""
        mgr = self.checkpoint_manager
        steps = sorted(mgr.all_steps() or [])
        good = [s for s in steps if s <= first_bad]
        if not good:
            raise guard_lib.GuardError(
                f"guard rollback needed at step {first_bad} but no "
                f"checkpoint predates the anomaly (steps on disk: "
                f"{steps}) -- save more often than anomalies arrive "
                "(cfg.save_every), or run guard_mode='skip'"
            )
        to_step = max(good)
        # A snapshot taken at step S holds S applied updates, so any
        # S > first_bad contains the poisoned one. With the guard on,
        # detection precedes the save at every chunk boundary, so this
        # list is normally empty -- it is belt for emergency preempt
        # saves that may have landed mid-anomaly.
        quarantined = [
            s for s in steps
            if s > first_bad
            and mgr.quarantine_step(s, reason="poisoned") is not None
        ]
        window = {
            "from_step": int(first_bad),
            "data_from": int(first_bad + offset),
            "data_to": int(last_bad + offset),
        }
        n_rollbacks = None
        if jax.process_index() == 0:
            state = guard_lib.record_rollback(self._guard_dir(), window)
            n_rollbacks = state["rollbacks"]
        obs.get_registry().inc("guard_rollbacks_total")
        rec = {
            "event": "guard_rollback",
            "step": last_bad + 1,
            # Keyed like the triggering verdict (the first step that
            # demanded rollback), so verdict, rollback record and any
            # guard-triggered capture join on one trace id.
            "trace_id": obs.step_trace_id(first_bad),
            "to_step": int(to_step),
            "first_bad": int(first_bad),
            "last_bad": int(last_bad),
            "data_from": window["data_from"],
            "data_to": window["data_to"],
            "quarantined": quarantined,
        }
        if n_rollbacks is not None:
            rec["n_rollbacks"] = n_rollbacks
        self._append_metrics(rec)
        self.logger.warning(
            "guard ROLLBACK: anomaly window steps [%d, %d] (data "
            "indices [%d, %d]); last-good checkpoint step %d; %d "
            "poisoned snapshot(s) quarantined -- exiting "
            "EXIT_ROLLBACK for the supervisor to relaunch",
            first_bad, last_bad, window["data_from"],
            window["data_to"], to_step, len(quarantined),
        )
        # Flight evidence: the ring holds the verdicts and the health
        # trail leading up to the anomaly.
        obs.dump_flight("guard_rollback")
        self._rolled_back = True
