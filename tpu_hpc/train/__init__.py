from tpu_hpc.train.metrics import ThroughputMeter, mfu  # noqa: F401
from tpu_hpc.train.trainer import Trainer, TrainState  # noqa: F401
