"""tpu_hpc.loadgen -- the SLO-driven load harness.

Seeded, scenario-diverse traffic for the serve engine (scenarios.py)
driven on a virtual clock so latency quantiles replay bit-identically
(harness.py), every request lifecycle emitted as schema-stamped
``obs`` records. The producer side of the perf-regression gate:
``python -m tpu_hpc.obs.regress`` consumes the JSONL these runs write.
"""
from tpu_hpc.loadgen.harness import (  # noqa: F401
    ENV_FAULTS,
    FAULT_DEFAULTS,
    FLEET_FAULT_KEYS,
    LoadHarness,
    LoadMeter,
    VirtualClock,
    fleet_faults_set,
    parse_faults,
    tenant_summary,
)
from tpu_hpc.loadgen.scenarios import (  # noqa: F401
    SCENARIOS,
    SLO_METRICS,
    LoadRequest,
    Scenario,
    TenantClass,
    build_scenario,
)

__all__ = [
    "ENV_FAULTS",
    "FAULT_DEFAULTS",
    "FLEET_FAULT_KEYS",
    "LoadHarness",
    "LoadMeter",
    "LoadRequest",
    "SCENARIOS",
    "SLO_METRICS",
    "Scenario",
    "TenantClass",
    "VirtualClock",
    "build_scenario",
    "fleet_faults_set",
    "parse_faults",
    "tenant_summary",
]
