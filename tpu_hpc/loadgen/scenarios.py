"""Seeded scenario catalog: reproducible serving traffic mixes.

Every scenario is generated from ONE ``numpy.random.default_rng(seed)``
stream, so the same (name, seed, knobs) always yields byte-identical
request lists -- the reproducibility the regress gate
(obs/regress.py) needs to call two runs of the same scenario
"the same workload". The catalog covers the traffic shapes the
DDP/FSDP characterization study (arxiv 2505.12832) argues systems must
be judged under -- measured distributions, not the single steady
replay `python -m tpu_hpc.serve` ships:

* ``steady``            Poisson arrivals, near-uniform lengths;
* ``bursty``            on/off bursts (B requests at burst rate, then
                        silence) -- queue-depth stress;
* ``heavy_tail``        lognormal prompt/output lengths clipped to the
                        engine's buckets -- slot-occupancy skew;
* ``multi_tenant``      three tenant classes (interactive/batch/
                        background) with priorities and per-tenant
                        SLOs;
* ``saturating_burst``  everything arrives at once, far past slot
                        capacity -- the admission-control acceptance
                        scenario (the lowest class MUST shed);
* ``colocate``          steady serving while a colocated training job
                        periodically steals the chip -- the stall
                        watermark's admission input;
* ``shared_prefix``     multi-tenant with a common per-tenant system
                        prompt and heavy-tail suffixes -- the paged
                        engine's prefix-reuse acceptance scenario
                        (serve/paging.py);
* ``decode_heavy``      chat-style short prompts with near-full
                        generation budgets -- the decode-bound mix
                        where ITL (not TTFT) is the product metric,
                        and the speculative-decoding acceptance
                        scenario (serve/spec.py): the prefill-bound
                        mixes above cannot show a decode-side win;
* ``diurnal``           day/night traffic: a sinusoidally-modulated
                        arrival rate (peaks oversubscribe a minimal
                        replica set, troughs idle it) over three
                        tenant classes with per-tenant system
                        prompts -- the serving-fleet acceptance
                        scenario (serve/fleet.py): autoscale rides
                        the swings, prefix affinity rides the
                        prompts, and the chaos harness injects a
                        mid-run weight swap + replica kill on top;
* ``long_idle_sessions`` returning chat users: first visits cache
                        their prompts, a filler wave floods the page
                        pool while the chatters idle, then everyone
                        returns at once -- the host-DRAM tier's
                        acceptance scenario (serve/tier.py): an
                        HBM-only pool must shed the return wave, a
                        tiered pool (parked pages spilled, refilled
                        on return) must shed none.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Mapping, Optional, Tuple

import numpy as np

from tpu_hpc.serve.scheduler import Request


# The per-tenant summary metrics an SLO may bound (what
# LoadHarness.summarize actually produces per tenant).
SLO_METRICS: Tuple[str, ...] = (
    "ttft_ms_p50", "ttft_ms_p95", "ttft_ms_p99",
    "itl_ms_p50", "itl_ms_p95",
)


@dataclasses.dataclass(frozen=True)
class TenantClass:
    """One traffic class: who it is, how much it sends, what it is
    owed. ``slo`` maps per-tenant summary metric names (the
    :data:`SLO_METRICS` set) to upper bounds in ms."""

    name: str
    priority: int = 0
    share: float = 1.0
    slo: Mapping[str, float] = dataclasses.field(default_factory=dict)

    def __post_init__(self):
        # Same discipline as parse_faults: a typoed SLO key that is
        # silently never violated would make every gate built on its
        # verdict vacuous.
        unknown = sorted(set(self.slo) - set(SLO_METRICS))
        if unknown:
            raise ValueError(
                f"tenant {self.name!r}: unknown SLO metric(s) "
                f"{unknown} (known: {', '.join(SLO_METRICS)})"
            )


@dataclasses.dataclass(frozen=True)
class LoadRequest:
    """One scheduled arrival: a serve Request plus its arrival time
    (ms on the harness clock)."""

    rid: str
    tenant: str
    priority: int
    arrival_ms: float
    prompt: Tuple[int, ...]
    max_new_tokens: int

    def to_request(self) -> Request:
        return Request(
            rid=self.rid,
            prompt=list(self.prompt),
            max_new_tokens=self.max_new_tokens,
            tenant=self.tenant,
            priority=self.priority,
        )


@dataclasses.dataclass(frozen=True)
class Scenario:
    """A fully materialized load scenario: the request schedule plus
    the policy/colocation knobs the harness consumes."""

    name: str
    seed: int
    tenants: Tuple[TenantClass, ...]
    requests: Tuple[LoadRequest, ...]
    # Admission backlog bound handed to serve.AdmissionPolicy.
    queue_limit: int = 32
    # Train+serve colocation: every `colocate_every` ticks the
    # harness charges `colocate_train_ms` of virtual time to a
    # colocated training step (0 = no colocation).
    colocate_train_ms: float = 0.0
    colocate_every: int = 0

    def tenant(self, name: str) -> TenantClass:
        for t in self.tenants:
            if t.name == name:
                return t
        raise KeyError(name)

    def header(self) -> dict:
        """The ``load_scenario`` record the harness emits first."""
        return {
            "scenario": self.name,
            "seed": self.seed,
            "n_requests": len(self.requests),
            "queue_limit": self.queue_limit,
            "colocate_train_ms": self.colocate_train_ms,
            "colocate_every": self.colocate_every,
            "tenants": {
                t.name: {
                    "priority": t.priority,
                    "share": t.share,
                    "slo": dict(t.slo),
                }
                for t in self.tenants
            },
        }


# -- building blocks ---------------------------------------------------
def poisson_arrivals(
    rng: np.random.Generator, n: int, rate_per_s: float,
) -> np.ndarray:
    """Arrival times (ms) of a Poisson process: cumulative exponential
    inter-arrival gaps."""
    if rate_per_s <= 0:
        raise ValueError(f"rate_per_s {rate_per_s} must be > 0")
    gaps_s = rng.exponential(1.0 / rate_per_s, size=n)
    return np.cumsum(gaps_s) * 1e3


def onoff_arrivals(
    rng: np.random.Generator,
    n: int,
    burst_size: int,
    burst_rate_per_s: float,
    off_ms: float,
) -> np.ndarray:
    """On/off bursts: ``burst_size`` Poisson arrivals at the burst
    rate, then ``off_ms`` of silence, repeated."""
    if burst_size < 1:
        raise ValueError(f"burst_size {burst_size} must be >= 1")
    if burst_rate_per_s <= 0:
        raise ValueError(
            f"burst_rate_per_s {burst_rate_per_s} must be > 0"
        )
    if off_ms < 0:
        raise ValueError(f"off_ms {off_ms} must be >= 0")
    out: List[float] = []
    t = 0.0
    while len(out) < n:
        take = min(burst_size, n - len(out))
        gaps = rng.exponential(1.0 / burst_rate_per_s, size=take) * 1e3
        for g in gaps:
            t += g
            out.append(t)
        t += off_ms
    return np.asarray(out)


def heavy_tail_lengths(
    rng: np.random.Generator,
    n: int,
    median: float,
    sigma: float,
    lo: int,
    hi: int,
) -> np.ndarray:
    """Lognormal lengths (median ``median``, shape ``sigma``) clipped
    into [lo, hi] -- the heavy-tailed prompt/output distributions real
    serving traffic shows."""
    if not 1 <= lo <= hi:
        raise ValueError(f"bad length range [{lo}, {hi}]")
    vals = rng.lognormal(mean=np.log(median), sigma=sigma, size=n)
    return np.clip(np.rint(vals), lo, hi).astype(np.int64)


def _assemble(
    name: str,
    seed: int,
    rng: np.random.Generator,
    tenants: Tuple[TenantClass, ...],
    tenant_of: np.ndarray,       # index into tenants, per request
    arrival_ms: np.ndarray,
    prompt_lens: np.ndarray,     # SUFFIX lengths when prefixes given
    max_new: np.ndarray,
    vocab_size: int,
    prefixes: Optional[Mapping[str, Tuple[int, ...]]] = None,
    **scenario_kw,
) -> Scenario:
    order = np.argsort(arrival_ms, kind="stable")
    reqs = []
    for k, i in enumerate(order):
        t = tenants[int(tenant_of[i])]
        plen = int(prompt_lens[i])
        prefix = tuple(prefixes.get(t.name, ())) if prefixes else ()
        reqs.append(LoadRequest(
            rid=f"{name[:2]}{k:05d}",
            tenant=t.name,
            priority=t.priority,
            arrival_ms=float(arrival_ms[i]),
            prompt=prefix + tuple(
                int(x) for x in rng.integers(0, vocab_size, size=plen)
            ),
            max_new_tokens=int(max_new[i]),
        ))
    return Scenario(
        name=name, seed=seed, tenants=tenants, requests=tuple(reqs),
        **scenario_kw,
    )


def diurnal_arrivals(
    rng: np.random.Generator,
    n: int,
    rate_per_s: float,
    cycles: float = 2.0,
    trough_frac: float = 0.2,
) -> np.ndarray:
    """Nonhomogeneous Poisson arrivals whose rate swings
    sinusoidally between ``trough_frac * rate_per_s`` and
    ``rate_per_s`` over ``cycles`` full day/night cycles across the
    ``n`` arrivals -- thinning over a homogeneous process at the
    peak rate, so the schedule stays a pure function of the rng
    stream. The period is derived from the EXPECTED span of ``n``
    arrivals at the mean rate, so the same shape scales with ``n``."""
    if not 0.0 < trough_frac <= 1.0:
        raise ValueError(
            f"trough_frac {trough_frac} must be in (0, 1]"
        )
    if cycles <= 0:
        raise ValueError(f"cycles {cycles} must be > 0")
    if rate_per_s <= 0:
        raise ValueError(f"rate_per_s {rate_per_s} must be > 0")
    mean_rate = rate_per_s * (1.0 + trough_frac) / 2.0
    period_s = (n / mean_rate) / cycles
    out: List[float] = []
    t = 0.0
    while len(out) < n:
        t += rng.exponential(1.0 / rate_per_s)
        phase = 2.0 * np.pi * (t / period_s)
        # rate(t)/rate_max in [trough_frac, 1]; start at the peak so
        # the run opens under load (the autoscale-up case) and dips
        # mid-run (the drain-down case).
        accept_p = trough_frac + (1.0 - trough_frac) * (
            0.5 * (1.0 + np.cos(phase))
        )
        if rng.random() < accept_p:
            out.append(t * 1e3)
    return np.asarray(out)


# -- the catalog -------------------------------------------------------
def build_scenario(
    name: str,
    seed: int = 0,
    n_requests: int = 32,
    vocab_size: int = 512,
    max_prompt: int = 16,
    max_new: int = 8,
    rate_per_s: float = 40.0,
) -> Scenario:
    """Materialize catalog scenario ``name``. ``max_prompt`` must not
    exceed the engine's largest prefill bucket and ``max_prompt +
    max_new`` must fit its cache capacity -- the caller (server
    ``--loadgen``, bench, tests) aligns these with its ServeConfig."""
    if name not in SCENARIOS:
        raise ValueError(
            f"unknown scenario {name!r} (catalog: "
            f"{', '.join(sorted(SCENARIOS))})"
        )
    if n_requests < 1:
        raise ValueError(f"n_requests {n_requests} must be >= 1")
    if max_prompt < 2 or max_new < 2:
        raise ValueError(
            f"max_prompt {max_prompt} and max_new {max_new} must both "
            "be >= 2 (the catalog's length distributions need a range)"
        )
    rng = np.random.default_rng(seed)
    n = n_requests
    lo_p = min(2, max_prompt)

    if name == "steady":
        tenants = (TenantClass("default", priority=0, share=1.0),)
        return _assemble(
            name, seed, rng, tenants,
            tenant_of=np.zeros(n, np.int64),
            arrival_ms=poisson_arrivals(rng, n, rate_per_s),
            prompt_lens=rng.integers(lo_p, max_prompt + 1, size=n),
            max_new=rng.integers(2, max_new + 1, size=n),
            vocab_size=vocab_size,
        )

    if name == "bursty":
        tenants = (TenantClass("default", priority=0, share=1.0),)
        return _assemble(
            name, seed, rng, tenants,
            tenant_of=np.zeros(n, np.int64),
            arrival_ms=onoff_arrivals(
                rng, n, burst_size=max(4, n // 4),
                burst_rate_per_s=rate_per_s * 10, off_ms=250.0,
            ),
            prompt_lens=rng.integers(lo_p, max_prompt + 1, size=n),
            max_new=rng.integers(2, max_new + 1, size=n),
            vocab_size=vocab_size,
        )

    if name == "heavy_tail":
        tenants = (TenantClass("default", priority=0, share=1.0),)
        return _assemble(
            name, seed, rng, tenants,
            tenant_of=np.zeros(n, np.int64),
            arrival_ms=poisson_arrivals(rng, n, rate_per_s),
            prompt_lens=heavy_tail_lengths(
                rng, n, median=max(2.0, max_prompt / 4), sigma=1.0,
                lo=1, hi=max_prompt,
            ),
            max_new=heavy_tail_lengths(
                rng, n, median=max(2.0, max_new / 3), sigma=0.8,
                lo=1, hi=max_new,
            ),
            vocab_size=vocab_size,
        )

    if name in ("multi_tenant", "saturating_burst"):
        tenants = (
            TenantClass(
                "interactive", priority=2, share=0.5,
                slo={"ttft_ms_p95": 400.0, "itl_ms_p95": 60.0},
            ),
            TenantClass(
                "batch", priority=1, share=0.3,
                slo={"ttft_ms_p95": 2000.0},
            ),
            TenantClass("background", priority=0, share=0.2),
        )
        shares = np.array([t.share for t in tenants])
        tenant_of = rng.choice(
            len(tenants), size=n, p=shares / shares.sum()
        )
        # Interactive sends short prompts/outputs; batch and
        # background send long ones.
        short = tenant_of == 0
        prompt_lens = np.where(
            short,
            rng.integers(lo_p, max(lo_p, max_prompt // 2) + 1, size=n),
            heavy_tail_lengths(
                rng, n, median=max(2.0, max_prompt / 2), sigma=0.6,
                lo=1, hi=max_prompt,
            ),
        )
        max_new_arr = np.where(
            short,
            rng.integers(1, max(2, max_new // 2) + 1, size=n),
            rng.integers(max(1, max_new // 2), max_new + 1, size=n),
        )
        if name == "saturating_burst":
            # Everyone at (nearly) once, way past slot capacity; a
            # tight backlog bound forces the policy's hand.
            arrival_ms = np.sort(rng.uniform(0.0, 5.0, size=n))
            return _assemble(
                name, seed, rng, tenants, tenant_of, arrival_ms,
                prompt_lens, max_new_arr, vocab_size,
                queue_limit=max(2, n // 8),
            )
        return _assemble(
            name, seed, rng, tenants, tenant_of,
            poisson_arrivals(rng, n, rate_per_s),
            prompt_lens, max_new_arr, vocab_size,
        )

    if name == "shared_prefix":
        # Multi-tenant with a COMMON per-tenant system prompt: every
        # request of a tenant opens with the same token prefix (half
        # the prompt budget), followed by a heavy-tail suffix. On a
        # paged engine with the prefix trie this is the
        # cache-efficiency acceptance scenario -- hit rate and the
        # pages (and prefill FLOPs) it saves are the point; on a slab
        # engine it degrades to a valid heavy-tail mix, so the same
        # seeded schedule measures both layouts.
        tenants = (
            TenantClass(
                "assistant", priority=1, share=0.45,
                slo={"ttft_ms_p95": 800.0},
            ),
            TenantClass(
                "search", priority=1, share=0.35,
                slo={"ttft_ms_p95": 800.0},
            ),
            TenantClass("batch", priority=0, share=0.2),
        )
        sys_len = min(max(2, max_prompt // 2), max_prompt - 1)
        # One fixed system prompt per tenant, drawn ONCE from the same
        # stream -- (name, seed) stays byte-identical.
        prefixes = {
            t.name: tuple(
                int(x)
                for x in rng.integers(0, vocab_size, size=sys_len)
            )
            for t in tenants
        }
        shares = np.array([t.share for t in tenants])
        tenant_of = rng.choice(
            len(tenants), size=n, p=shares / shares.sum()
        )
        suffix_hi = max(1, max_prompt - sys_len)
        suffix_lens = heavy_tail_lengths(
            rng, n, median=max(2.0, suffix_hi / 3), sigma=0.8,
            lo=1, hi=suffix_hi,
        )
        return _assemble(
            name, seed, rng, tenants, tenant_of,
            poisson_arrivals(rng, n, rate_per_s),
            suffix_lens,
            heavy_tail_lengths(
                rng, n, median=max(2.0, max_new / 3), sigma=0.8,
                lo=1, hi=max_new,
            ),
            vocab_size,
            prefixes=prefixes,
        )

    if name == "decode_heavy":
        # Chat-style decode-bound traffic: prompts a fraction of the
        # budget, generation budgets near max_new -- the inverse of
        # heavy_tail's long-prompt/short-output skew. Here the decode
        # loop IS the latency (prefill is one short bucket per
        # request), so this is where speculative decoding's
        # tokens-per-verify win lands in the ITL quantiles.
        tenants = (TenantClass("chat", priority=0, share=1.0),)
        hi_p = max(lo_p, max_prompt // 4)
        return _assemble(
            name, seed, rng, tenants,
            tenant_of=np.zeros(n, np.int64),
            arrival_ms=poisson_arrivals(rng, n, rate_per_s),
            prompt_lens=rng.integers(lo_p, hi_p + 1, size=n),
            max_new=rng.integers(
                max(2, (3 * max_new) // 4), max_new + 1, size=n
            ),
            vocab_size=vocab_size,
        )

    if name == "diurnal":
        # Day/night swings over three classes WITH per-tenant system
        # prompts: the fleet acceptance scenario. ``background`` is
        # the SLO-class floor -- the only class the zero-shed-above-
        # the-floor contract allows admission control to drop under
        # pressure. Generous SLO bounds: the chaos runs this gates
        # (replica kill + weight swap mid-run) must breach them only
        # when failure handling actually regresses, not on ordinary
        # peak queueing.
        tenants = (
            TenantClass(
                "interactive", priority=2, share=0.45,
                slo={"ttft_ms_p95": 4000.0},
            ),
            TenantClass(
                "batch", priority=1, share=0.35,
                slo={"ttft_ms_p95": 12000.0},
            ),
            TenantClass("background", priority=0, share=0.2),
        )
        sys_len = min(max(2, max_prompt // 2), max_prompt - 1)
        prefixes = {
            t.name: tuple(
                int(x)
                for x in rng.integers(0, vocab_size, size=sys_len)
            )
            for t in tenants
        }
        shares = np.array([t.share for t in tenants])
        tenant_of = rng.choice(
            len(tenants), size=n, p=shares / shares.sum()
        )
        suffix_hi = max(1, max_prompt - sys_len)
        return _assemble(
            name, seed, rng, tenants, tenant_of,
            diurnal_arrivals(rng, n, rate_per_s),
            heavy_tail_lengths(
                rng, n, median=max(2.0, suffix_hi / 3), sigma=0.8,
                lo=1, hi=suffix_hi,
            ),
            rng.integers(2, max_new + 1, size=n),
            vocab_size,
            prefixes=prefixes,
        )

    if name == "long_idle_sessions":
        # Returning chat users: a wave of first visits caches its
        # prompts in the trie, a filler wave floods the page pool
        # while the chatters idle, then every chatter comes back at
        # once with its old prompt plus a short new turn. An
        # HBM-only pool evicted the parked prompts to seat the
        # fillers, so the return wave re-prefills from scratch,
        # drains slowly, and overflows the (tight) backlog bound --
        # returns shed. A host-tiered pool SPILLED the parked pages
        # instead; the return wave prefix-hits after a cheap
        # refill hop and drains fast -- zero returns shed. The
        # tenant split keeps the contrast measurable per class
        # (TTFT-on-return is ``tenants["return"]``'s quantiles).
        n_sessions = max(1, n // 3)
        n_fill = max(1, n // 3)
        n_return = max(1, n - n_sessions - n_fill)
        ret_suffix = max(1, min(max_new, max_prompt // 4))
        first_hi = max(lo_p, max_prompt - ret_suffix)
        first_lo = max(lo_p, first_hi // 2)
        tenants = (
            TenantClass("chat", priority=1, share=0.34),
            TenantClass("filler", priority=0, share=0.33),
            TenantClass("return", priority=1, share=0.33),
        )
        first_prompts = [
            tuple(
                int(x) for x in rng.integers(
                    0, vocab_size,
                    size=int(rng.integers(first_lo, first_hi + 1)),
                )
            )
            for _ in range(n_sessions)
        ]
        idle_gap_ms = 1000.0
        chat_arr = poisson_arrivals(rng, n_sessions, rate_per_s)
        fill_arr = (
            float(chat_arr.max()) + idle_gap_ms
            + poisson_arrivals(rng, n_fill, rate_per_s)
        )
        # The whole cohort returns in a tight wave (3x the base
        # rate): the drain-rate contrast (prefix hit vs full
        # re-prefill) is what decides whether the backlog bound
        # overflows.
        ret_arr = (
            float(fill_arr.max()) + idle_gap_ms
            + poisson_arrivals(rng, n_return, rate_per_s * 3)
        )
        reqs = []
        for i in range(n_sessions):
            reqs.append((
                "chat", 1, float(chat_arr[i]), first_prompts[i],
                int(rng.integers(2, max_new + 1)),
            ))
        for i in range(n_fill):
            plen = int(rng.integers(
                max(lo_p, (3 * max_prompt) // 4), max_prompt + 1
            ))
            reqs.append((
                "filler", 0, float(fill_arr[i]),
                tuple(
                    int(x)
                    for x in rng.integers(0, vocab_size, size=plen)
                ),
                int(rng.integers(2, max_new + 1)),
            ))
        for i in range(n_return):
            base = first_prompts[i % n_sessions]
            suffix = tuple(
                int(x)
                for x in rng.integers(0, vocab_size, size=ret_suffix)
            )
            reqs.append((
                "return", 1, float(ret_arr[i]), base + suffix,
                int(rng.integers(2, max_new + 1)),
            ))
        reqs.sort(key=lambda r: r[2])
        return Scenario(
            name=name, seed=seed, tenants=tenants,
            requests=tuple(
                LoadRequest(
                    rid=f"{name[:2]}{k:05d}",
                    tenant=t, priority=p, arrival_ms=a,
                    prompt=prompt, max_new_tokens=mn,
                )
                for k, (t, p, a, prompt, mn) in enumerate(reqs)
            ),
            # Tight backlog: the return wave must DRAIN, not park --
            # the shed-vs-zero-shed contrast is the acceptance
            # signal, and an unbounded queue would absorb it.
            queue_limit=max(2, n // 8),
        )

    assert name == "colocate"
    # Two classes: when the colocated train step trips the stall
    # watermark, admission control sheds `background` and the
    # `online` class keeps its SLO -- the class-protection property
    # the scenario exists to measure.
    tenants = (
        TenantClass(
            "online", priority=1, share=0.7,
            slo={"ttft_ms_p95": 600.0},
        ),
        TenantClass("background", priority=0, share=0.3),
    )
    shares = np.array([t.share for t in tenants])
    return _assemble(
        name, seed, rng, tenants,
        tenant_of=rng.choice(
            len(tenants), size=n, p=shares / shares.sum()
        ),
        arrival_ms=poisson_arrivals(rng, n, rate_per_s),
        prompt_lens=rng.integers(lo_p, max_prompt + 1, size=n),
        max_new=rng.integers(2, max_new + 1, size=n),
        vocab_size=vocab_size,
        # A 40 ms train step every 8 serve ticks: >3x a default 8 ms
        # decode tick, so the stall watermark trips by design.
        colocate_train_ms=40.0,
        colocate_every=8,
    )


SCENARIOS: Tuple[str, ...] = (
    "steady", "bursty", "heavy_tail", "multi_tenant",
    "saturating_burst", "colocate", "shared_prefix", "decode_heavy",
    "diurnal", "long_idle_sessions",
)
