"""The load harness: drive the serve engine with a seeded scenario,
emit every request's lifecycle as schema-stamped ``obs`` records.

Timing runs on a **virtual clock**: each engine call charges a modeled
cost (``decode_step_ms`` per decode tick, ``prefill_ms_per_token`` x
padded bucket per admission, plus the scenario's colocated-train
steals), so a seeded run's TTFT/ITL/goodput quantiles are a pure
function of (scenario, seed, engine shape) -- bit-identical on replay,
which is what lets obs/regress.py treat ANY diff as signal. The engine
calls themselves are real (real prefill/decode programs, real tokens);
only the clock is modeled. Wall-clock serving throughput remains
`python -m tpu_hpc.serve` / `bench.py --serve`'s job -- this harness
measures *scheduling behavior* (queueing, admission, tenant isolation)
that machine noise would otherwise drown.

Fault injection (``TPU_HPC_LOADGEN_FAULTS``, the TPU_HPC_FAULTS
spelling): ``prefill_delay=1.5`` / ``decode_delay=2.0`` multiply the
modeled costs -- the injected-latency path the regress gate's CI smoke
proves itself against.

Lifecycle events (obs/schema.py): ``load_scenario`` header, then per
request ``lg_arrival`` -> ``lg_admit`` -> ``lg_first_token`` ->
``lg_token`` (ring-only: per-token cadence is flight-recorder
forensics, not sink volume) -> ``lg_finish``, or ``lg_shed`` when
admission control drops it; the scheduler's own ``admission`` events
land in the same sink. The ServeMeter rides along on the virtual
clock, so ``serve_summary`` -- and through it the obs.report quantile
machinery -- works on load runs for free.
"""
from __future__ import annotations

import os
from typing import Callable, Dict, List, Optional

from tpu_hpc.obs import (
    AnomalyCapture,
    StallDetector,
    emit_span,
    get_bus,
    get_registry,
    request_trace_id,
    trace_id_for,
)
from tpu_hpc.obs.quantiles import quantile
from tpu_hpc.serve.metrics import ServeMeter
from tpu_hpc.serve.scheduler import AdmissionPolicy, ContinuousBatcher
from tpu_hpc.loadgen.scenarios import Scenario

ENV_FAULTS = "TPU_HPC_LOADGEN_FAULTS"

# Faults only the multi-replica fleet harness (serve/fleet.py) can
# inject: a single-engine LoadHarness has no replica to kill, slow
# down, or hand a corrupt weight swap. LoadHarness hard-rejects them
# (below) -- a fleet fault silently doing nothing on a single-engine
# run is exactly the vacuous-chaos-test failure this parser exists to
# prevent.
FLEET_FAULT_KEYS = ("replica_kill_at", "swap_corrupt", "slow_replica")


def _cost_multiplier(v: str) -> float:
    x = float(v)
    if x <= 0:
        raise ValueError(v)
    return x


def _fleet_tick(v: str) -> int:
    x = int(v)
    if x < 0:
        raise ValueError(v)
    return x


def _bool01(v: str) -> bool:
    x = int(v)
    if x not in (0, 1):
        raise ValueError(v)
    return bool(x)


def _slow_replica(v: str) -> "tuple[int, float]":
    idx, sep, factor = v.partition(":")
    if not sep:
        raise ValueError(v)
    i, f = int(idx), float(factor)
    if i < 0 or f <= 0:
        raise ValueError(v)
    return (i, f)


# key -> (cast, expected-type text) for the shared typed parser
# (resilience/faults.parse_kv_spec -- one loop, one error discipline
# for TPU_HPC_FAULTS and TPU_HPC_LOADGEN_FAULTS alike).
_FAULT_CASTS = {
    "prefill_delay": (
        _cost_multiplier, "a positive number (cost multiplier, > 0)",
    ),
    "decode_delay": (
        _cost_multiplier, "a positive number (cost multiplier, > 0)",
    ),
    "replica_kill_at": (
        _fleet_tick, "a non-negative integer (fleet tick index)",
    ),
    "swap_corrupt": (_bool01, "0 or 1"),
    "slow_replica": (
        _slow_replica,
        "'<replica>:<factor>' (non-negative int : factor > 0)",
    ),
}

FAULT_DEFAULTS: Dict[str, object] = {
    "prefill_delay": 1.0,
    "decode_delay": 1.0,
    "replica_kill_at": None,
    "swap_corrupt": False,
    "slow_replica": None,
}


def parse_faults(spec: Optional[str] = None) -> Dict[str, object]:
    """``"prefill_delay=1.5,replica_kill_at=40"`` -> fault dict over
    :data:`FAULT_DEFAULTS`. Unknown keys AND malformed values raise a
    typed error naming the key, the full spec and the expected type
    (resilience/faults.py's parse discipline, shared via
    ``parse_kv_spec``): a typoed fault silently injecting nothing
    would make the gate's failure proof vacuous."""
    from tpu_hpc.resilience.faults import parse_kv_spec

    if spec is None:
        spec = os.environ.get(ENV_FAULTS, "")
    out: Dict[str, object] = dict(FAULT_DEFAULTS)
    out.update(parse_kv_spec(spec, ENV_FAULTS, _FAULT_CASTS))
    return out


def fleet_faults_set(faults: Dict[str, object]) -> "list[str]":
    """The fleet-only fault keys armed (non-default) in ``faults``.
    Identity checks, not ``in (None, False)``: ``replica_kill_at=0``
    is a legal armed value that compares equal to False, and
    treating it as unarmed would let a kill-at-tick-0 fault slip
    silently through the single-engine harness's guard."""
    return [
        k for k in FLEET_FAULT_KEYS
        if not (faults.get(k) is None or faults.get(k) is False)
    ]


class VirtualClock:
    """Monotonic seconds, advanced explicitly. Calling it returns the
    current time, so it drops in wherever ``time.perf_counter``
    goes."""

    def __init__(self, t0: float = 0.0):
        self._t = float(t0)

    def __call__(self) -> float:
        return self._t

    def advance(self, dt_s: float) -> None:
        if dt_s < 0:
            raise ValueError(f"cannot advance clock by {dt_s}")
        self._t += dt_s

    def jump_to(self, t_s: float) -> None:
        """Set the clock to an absolute time, BACKWARD jumps allowed.
        Single-timeline consumers never need this; the fleet harness
        (serve/fleet.py) multiplexes N per-replica timelines through
        one meter clock -- each replica tick rewinds the shared clock
        to that replica's local time, so concurrent replicas charge
        OVERLAPPING virtual intervals instead of serializing (adding
        a replica must reduce latency, not add its tick costs to the
        global clock). Per-request timestamps stay monotonic: a
        request lives on one replica's timeline at a time, and
        redispatch only ever moves it to a replica whose local time
        has already passed the detection timeout."""
        self._t = float(t_s)


# Modeled KV-traffic factors for the paged read paths
# (tpu_hpc.kernels.paged_attention), relative to the gather/fp16
# baseline the cost model was calibrated against. The gather path
# materializes every slot's pages into a dense per-step copy before
# the flash call (pool read + copy write + copy re-read, ~3 HBM
# passes over the context); the pallas kernel walks the block table
# in-kernel and touches each page once. int8 pages halve the bytes
# the pool read moves (the fp32 scale side array is noise); under
# gather the dense copy still moves at the activation dtype, so only
# the pool-read pass shrinks. The (gather, none) entry MUST stay
# exactly 1.0 -- every banked loadgen row before ISSUE 20 was charged
# on that path, and the multiplier below is skipped at 1.0 so legacy
# histories stay byte-identical.
_KV_TRAFFIC = {
    ("gather", "none"): 1.0,
    ("pallas", "none"): 1 / 3,
    ("gather", "int8"): 2 / 3,
    ("pallas", "int8"): 1 / 6,
}
# How much of each charge is KV-bandwidth: decode is famously
# KV-bound (one token of compute against the whole context's reads),
# prefill is compute-bound with KV writes a small slice.
_KV_DECODE_FRAC = 0.6
_KV_PREFILL_FRAC = 0.2


class _CostModelEngine:
    """Engine proxy: runs the real programs, charges modeled virtual
    time for each. Placed between batcher and engine so the meter's
    timestamps (taken inside the batcher, after each engine call
    returns) see prefill/decode costs without the batcher knowing
    about clocks.

    Paged engines (serve/paging.py) pass through transparently:
    ``prefill_step`` charges each CHUNK's padded tokens as they
    forward (so chunked prefill's TTFT/ITL interleaving shows up on
    the virtual clock exactly as it would on chips, and a prefix hit's
    skipped chunks cost nothing -- the hit is visible in the
    quantiles, not just the counters); everything else of the paged
    protocol (admit/release/validate_request/stats) delegates via
    ``__getattr__``."""

    def __init__(
        self,
        engine,
        clock: VirtualClock,
        decode_step_ms: float,
        prefill_ms_per_token: float,
        faults: Dict[str, float],
        draft_cost_frac: float = 0.15,
        hop_ms_per_page: float = 0.5,
    ):
        self._engine = engine
        self._clock = clock
        # Host-tier hop cost (serve/tier.py): each page spilled to or
        # refilled from host DRAM charges this much modeled time --
        # ~an order cheaper per token than prefill (a DMA, not a
        # forward pass), which is the whole tier thesis. Engines
        # without a tier never move pages, so legacy runs charge 0
        # and stay byte-identical.
        self._hop_s_per_page = hop_ms_per_page / 1e3
        self._decode_s = decode_step_ms / 1e3 * faults["decode_delay"]
        self._prefill_s_per_token = (
            prefill_ms_per_token / 1e3 * faults["prefill_delay"]
        )
        # Kernel/quant read-path discount (_KV_TRAFFIC above): paged
        # engines advertise kv_kernel/kv_quant (serve/paging.py);
        # slab engines have neither attribute and charge the
        # calibrated baseline untouched.
        traffic = _KV_TRAFFIC[(
            getattr(engine, "kv_kernel", "gather"),
            getattr(engine, "kv_quant", "none"),
        )]
        if traffic != 1.0:
            self._decode_s *= (
                (1 - _KV_DECODE_FRAC) + _KV_DECODE_FRAC * traffic
            )
            self._prefill_s_per_token *= (
                (1 - _KV_PREFILL_FRAC) + _KV_PREFILL_FRAC * traffic
            )
        # Speculative cost model (serve/spec.py): one verify step
        # charges ONE decode forward -- the whole premise is that a
        # (k+1)-token forward is latency-bound like a 1-token one --
        # plus, for draft-model speculation, k draft steps at
        # ``draft_cost_frac`` of a target step each (a ~10x smaller
        # draft is ~0.1-0.2x per step). Prompt-lookup drafting is
        # host-side and charges nothing. The draft's prefill charges
        # at the same fraction per forwarded token.
        self._draft_frac = draft_cost_frac
        self.draft_charged_s = 0.0
        # Cumulative prefill charge: the harness subtracts its
        # per-tick delta before feeding the stall detector -- an
        # admission tick is EXPECTED to be long (one 512-token bucket
        # costs ~16 decode ticks of modeled time), and letting it
        # trip the watermark would shed tenants on ordinary prefill
        # scheduling, not on stalls (review finding).
        self.prefill_charged_s = 0.0

    def __getattr__(self, name):
        # Cost-neutral surface (serve_cfg, the paged protocol's
        # release/validate_request, stats/occupancy reads) delegates;
        # only the compute calls below (and admit/prefetch_prompt,
        # which charge the host-tier hop) cost time.
        return getattr(self._engine, name)

    def _draft_forwarded(self) -> int:
        spec = getattr(self._engine, "spec", None)
        if spec is None or spec.draft is None:
            return 0
        return spec.draft.prefill_forwarded_total

    def _hop_pages(self) -> int:
        tier = getattr(self._engine, "host_tier", None)
        if tier is None:
            return 0
        return (
            tier.stats["kv_spill_pages"] + tier.stats["kv_refill_pages"]
        )

    def _charge_hop(self, pages_before: int) -> None:
        """Charge the tier pages moved since ``pages_before``. Folded
        into ``prefill_charged_s``: like a prefill chunk, a hop is
        EXPECTED admission-path work, and the stall detector must not
        shed tenants on it."""
        pages = self._hop_pages() - pages_before
        if pages > 0:
            cost = self._hop_s_per_page * pages
            self.prefill_charged_s += cost
            self._clock.advance(cost)

    def admit(self, *args, **kwargs):
        # A host-tier admit may spill parked pages to make room; the
        # charge must land even when admission then fails (the bytes
        # moved either way).
        before = self._hop_pages()
        try:
            return self._engine.admit(*args, **kwargs)
        finally:
            self._charge_hop(before)

    def prefetch_prompt(self, prompt):
        before = self._hop_pages()
        try:
            return self._engine.prefetch_prompt(prompt)
        finally:
            self._charge_hop(before)

    def prefill(self, idx: int, prompt: List[int]) -> int:
        out = self._engine.prefill(idx, prompt)
        bucket = self._engine.serve_cfg.bucket_for(len(prompt))
        cost = self._prefill_s_per_token * bucket
        self.prefill_charged_s += cost
        self._clock.advance(cost)
        return out

    def prefill_step(self, idx: int):
        before = self._engine.prefill_forwarded_total
        d_before = self._draft_forwarded()
        out = self._engine.prefill_step(idx)
        cost = self._prefill_s_per_token * (
            self._engine.prefill_forwarded_total - before
        )
        draft_cost = (
            self._prefill_s_per_token * self._draft_frac
            * (self._draft_forwarded() - d_before)
        )
        self.draft_charged_s += draft_cost
        self.prefill_charged_s += cost + draft_cost
        self._clock.advance(cost + draft_cost)
        return out

    def decode(self, tokens, positions, active=None):
        if active is not None:
            out = self._engine.decode(tokens, positions, active)
        else:
            out = self._engine.decode(tokens, positions)
        self._clock.advance(self._decode_s)
        return out

    def spec_decode(self, *args, **kwargs):
        out = self._engine.spec_decode(*args, **kwargs)
        spec = self._engine.spec
        cost = self._decode_s
        if spec.draft is not None:
            draft_cost = self._decode_s * self._draft_frac * spec.cfg.k
            self.draft_charged_s += draft_cost
            cost += draft_cost
        self._clock.advance(cost)
        return out


class LoadMeter(ServeMeter):
    """ServeMeter + the lg_* lifecycle events and per-tenant
    aggregation. ``tenant_of[rid]`` is filled by the harness at
    submission time."""

    def __init__(
        self,
        metrics_path: Optional[str] = None,
        clock: Optional[Callable[[], float]] = None,
    ):
        super().__init__(metrics_path=metrics_path, clock=clock)
        self.tenant_of: Dict[str, str] = {}
        self.ttft_ms: Dict[str, List[float]] = {}   # per tenant
        self.itl_ms: Dict[str, List[float]] = {}
        self.finished_by: Dict[str, int] = {}
        self.queued_by: Dict[str, int] = {}         # waited >= 1 tick
        self.shed_by: Dict[str, int] = {}
        # Set by the harness before each batcher tick: "queued" means
        # submitted BEFORE the tick that admitted it. queue_ms alone
        # cannot tell (an earlier slot's prefill charge advances the
        # shared clock between two same-tick admissions -- review
        # finding).
        self.tick_start_s = 0.0

    def _tenant(self, rid: str) -> str:
        return self.tenant_of.get(rid, "default")

    def admitted(self, rid: str, prefill_tokens: int = 0) -> None:
        super().admitted(rid, prefill_tokens=prefill_tokens)
        trace = self.traces[rid]
        queue_ms = 1e3 * (trace.t_admit - trace.t_submit)
        tenant = self._tenant(rid)
        queued = trace.t_submit < self.tick_start_s
        if queued:
            self.queued_by[tenant] = self.queued_by.get(tenant, 0) + 1
        get_bus().emit(
            "lg_admit", sink=self.metrics_path,
            rid=rid, trace_id=self.trace_ids.get(rid),
            tenant=tenant, queue_ms=queue_ms,
            prefill_tokens=prefill_tokens, queued=queued,
        )

    def token(self, rid: str, first: bool = False) -> None:
        super().token(rid, first=first)
        trace = self.traces[rid]
        tenant = self._tenant(rid)
        if first:
            ttft_ms = 1e3 * (trace.t_first - trace.t_submit)
            self.ttft_ms.setdefault(tenant, []).append(ttft_ms)
            get_bus().emit(
                "lg_first_token", sink=self.metrics_path,
                rid=rid, trace_id=self.trace_ids.get(rid),
                tenant=tenant, ttft_ms=ttft_ms,
            )
        else:
            itl = 1e3 * (trace.token_times[-1] - trace.token_times[-2])
            self.itl_ms.setdefault(tenant, []).append(itl)
            # Ring-only (no sink): per-token cadence at decode rate is
            # flight-recorder forensics, not per-run sink volume --
            # but it still carries the trace id, so a flight dump's
            # token cadence joins the request timeline.
            get_bus().emit(
                "lg_token", rid=rid,
                trace_id=self.trace_ids.get(rid), itl_ms=itl,
            )

    def finished(self, rid: str) -> None:
        trace = self.traces[rid]
        tenant = self._tenant(rid)
        super().finished(rid)
        self.finished_by[tenant] = self.finished_by.get(tenant, 0) + 1
        get_bus().emit(
            "lg_finish", sink=self.metrics_path,
            rid=rid, trace_id=self.trace_ids.get(rid),
            tenant=tenant, tokens=len(trace.token_times),
            total_ms=1e3 * (trace.t_done - trace.t_submit),
        )

    def request_shed(self, rid: str, reason: str = "") -> None:
        tenant = self._tenant(rid)
        super().request_shed(rid, reason=reason)
        self.shed_by[tenant] = self.shed_by.get(tenant, 0) + 1
        get_bus().emit(
            "lg_shed", sink=self.metrics_path,
            rid=rid,
            trace_id=self.trace_ids.get(rid, request_trace_id(rid)),
            tenant=tenant, reason=reason,
        )


def tenant_summary(
    scenario: Scenario,
    meter: "LoadMeter",
    spec_by_tenant: Optional[Dict[str, Dict[str, int]]] = None,
):
    """Per-tenant quantiles, lifecycle counts and SLO verdicts from a
    LoadMeter -- ``(tenants, slo_violations, violated_tenants)``. One
    aggregation for the single-engine LoadHarness and the fleet
    harness (serve/fleet.py): the SLO verdict logic must not fork.

    ``violated_tenants`` keeps the violating tenant NAMES next to the
    composite ``"<tenant>.<metric>"`` strings -- consumers (the
    capture trigger) must not re-parse the composites (a tenant name
    containing '.' would truncate)."""
    spec_by_tenant = spec_by_tenant or {}
    tenants = {}
    slo_violations: List[str] = []
    violated_tenants: List[str] = []
    for t in scenario.tenants:
        ttfts = sorted(meter.ttft_ms.get(t.name, []))
        itls = sorted(meter.itl_ms.get(t.name, []))
        entry = {
            "priority": t.priority,
            "finished": meter.finished_by.get(t.name, 0),
            "shed": meter.shed_by.get(t.name, 0),
            "queued": meter.queued_by.get(t.name, 0),
            "ttft_ms_p50": quantile(ttfts, 0.50),
            "ttft_ms_p95": quantile(ttfts, 0.95),
            "ttft_ms_p99": quantile(ttfts, 0.99),
            "itl_ms_p50": quantile(itls, 0.50),
            "itl_ms_p95": quantile(itls, 0.95),
        }
        st = spec_by_tenant.get(t.name)
        if st is not None:
            # Per-request-class acceptance evidence: the banked
            # rows report acceptance per scenario AND per tenant.
            entry["spec_drafted"] = st["drafted"]
            entry["spec_accepted"] = st["accepted"]
            entry["acceptance_rate"] = (
                st["accepted"] / st["drafted"]
                if st["drafted"] else 0.0
            )
        if t.slo:
            # entry[k], not .get(): TenantClass validated the SLO
            # keys against SLO_METRICS, and a drift between that
            # set and what summarize produces must crash, not
            # silently never-violate.
            violated = sorted(
                k for k, bound in t.slo.items()
                if entry[k] > bound
            )
            entry["slo"] = dict(t.slo)
            entry["slo_violated"] = violated
            slo_violations += [f"{t.name}.{k}" for k in violated]
            if violated:
                violated_tenants.append(t.name)
        tenants[t.name] = entry
    return tenants, slo_violations, violated_tenants


class LoadHarness:
    """One scenario end to end: submit arrivals on schedule, tick the
    batcher, watch the stall watermark, aggregate per-tenant SLOs."""

    def __init__(
        self,
        engine,
        scenario: Scenario,
        metrics_path: Optional[str] = None,
        decode_step_ms: float = 8.0,
        prefill_ms_per_token: float = 0.25,
        policy: Optional[AdmissionPolicy] = None,
        stall_factor: float = 3.0,
        faults: Optional[Dict[str, float]] = None,
        capture: Optional[AnomalyCapture] = None,
        hop_ms_per_page: float = 0.5,
    ):
        self.scenario = scenario
        self.metrics_path = metrics_path
        # Anomaly-triggered capture (obs/trace.py): a stall-watermark
        # trip or an SLO breach fires ONE bounded profiler trace +
        # flight dump keyed by the triggering trace id. None = off.
        self.capture = capture
        self.clock = VirtualClock()
        faults = faults if faults is not None else parse_faults()
        armed = fleet_faults_set(faults)
        if armed:
            # A fleet fault on a single-engine harness has no replica
            # to kill/slow/corrupt -- silently ignoring it would make
            # the chaos test it belongs to pass vacuously (the
            # unknown-key discipline, applied to misplaced keys).
            raise ValueError(
                f"fleet fault(s) {armed} need the fleet harness "
                "(serve/fleet.FleetHarness); LoadHarness drives one "
                "engine and cannot inject them"
            )
        self.engine = _CostModelEngine(
            engine, self.clock, decode_step_ms, prefill_ms_per_token,
            faults, hop_ms_per_page=hop_ms_per_page,
        )
        self.meter = LoadMeter(metrics_path=metrics_path,
                               clock=self.clock)
        self.detector = StallDetector(
            window=16, factor=stall_factor, min_samples=5,
        )
        self._stalled = False
        self.batcher = ContinuousBatcher(
            self.engine,
            meter=self.meter,
            policy=policy or AdmissionPolicy(
                queue_limit=scenario.queue_limit
            ),
            stall_signal=lambda: self._stalled,
        )
        self._occupancy: List[float] = []

    # -- the drive loop -----------------------------------------------
    def run(
        self,
        n_devices: int = 1,
        n_params: Optional[int] = None,
        peak_flops_per_device: Optional[float] = None,
        max_ticks: Optional[int] = None,
        tick_cb=None,
        extra: Optional[dict] = None,
    ) -> dict:
        """drive() then summarize() -- the one-call convenience."""
        self.drive(max_ticks=max_ticks, tick_cb=tick_cb)
        return self.summarize(
            n_devices=n_devices, n_params=n_params,
            peak_flops_per_device=peak_flops_per_device, extra=extra,
        )

    def _submit_arrival(self, lr) -> None:
        self.meter.tenant_of[lr.rid] = lr.tenant
        get_bus().emit(
            "lg_arrival", sink=self.metrics_path,
            rid=lr.rid, trace_id=request_trace_id(lr.rid),
            tenant=lr.tenant,
            arrival_ms=lr.arrival_ms,
            prompt_len=len(lr.prompt),
            max_new_tokens=lr.max_new_tokens,
            priority=lr.priority,
        )
        self.batcher.submit(lr.to_request())

    def drive(
        self, max_ticks: Optional[int] = None, tick_cb=None,
    ) -> None:
        sc = self.scenario
        bus = get_bus()
        bus.emit("load_scenario", sink=self.metrics_path, **sc.header())
        arrivals = list(sc.requests)  # already arrival-sorted
        if max_ticks is not None:
            budget = max_ticks
        else:
            budget = (
                sum(r.max_new_tokens + 1 for r in arrivals)
                + len(arrivals) + 16
            )
            if getattr(self.engine, "is_paged", False):
                from tpu_hpc.serve.scheduler import paged_drain_bound

                budget += paged_drain_bound(self.engine, arrivals)
        try:
            self._drive_loop(arrivals, budget, tick_cb)
        finally:
            if self.capture is not None:
                # A capture window still open when the drive ends (or
                # aborts on the budget) must not leak its profiler
                # trace.
                self.capture.close()

    def _drive_loop(self, arrivals, budget, tick_cb) -> None:
        sc = self.scenario
        i = 0
        tick = 0
        while i < len(arrivals) or not self.batcher.done:
            # A request is "queued" iff it was submitted before this
            # iteration began -- stamp the boundary BEFORE this
            # tick's submissions (and before any colocation advance,
            # which would otherwise age same-tick arrivals).
            self.meter.tick_start_s = self.clock()
            now_ms = self.clock() * 1e3
            while i < len(arrivals) and arrivals[i].arrival_ms <= now_ms:
                self._submit_arrival(arrivals[i])
                i += 1
            if self.batcher.done:
                # Idle: jump the virtual clock to the next arrival
                # instead of spinning empty decode ticks -- and
                # submit it DIRECTLY: the ms->s->ms float round trip
                # can land the clock a hair short of arrival_ms, and
                # re-testing the due-predicate on that value would
                # advance(0) forever (review finding: a reproducible
                # livelock on ~0.7% of uniform arrival times).
                lr = arrivals[i]
                self.clock.advance(
                    max(lr.arrival_ms / 1e3 - self.clock(), 0.0)
                )
                self._submit_arrival(lr)
                i += 1
                continue
            if tick >= budget:
                raise RuntimeError(
                    f"load harness did not drain within {budget} ticks"
                )
            t_before = self.clock()
            if (
                sc.colocate_every > 0
                and tick % sc.colocate_every == 0
            ):
                # The colocated training job steals the chip for one
                # step; span events make the theft attributable in the
                # report's phase table. emit_span with the VIRTUAL
                # duration (a wall-clock span here would leak machine
                # noise into an otherwise deterministic run).
                self.clock.advance(sc.colocate_train_ms / 1e3)
                emit_span(
                    "colocated_train_step",
                    sc.colocate_train_ms / 1e3,
                    sink=self.metrics_path, step=tick,
                )
            prefill_before = self.engine.prefill_charged_s
            decode_before = self.batcher.stats["decode_steps"]
            self.batcher.step()
            # The watermark watches decode cadence + colocation
            # steals; this tick's prefill admission charges are
            # excluded (expected work, not a stall -- see
            # _CostModelEngine.prefill_charged_s).
            tick_s = (
                self.clock() - t_before
                - (self.engine.prefill_charged_s - prefill_before)
            )
            if self.batcher.stats["decode_steps"] > decode_before:
                tick_tid = trace_id_for("tick", tick)
                info = self.detector.observe(
                    tick, tick_s, sink=self.metrics_path,
                    trace_id=tick_tid,
                )
                self._stalled = info is not None
                if self._stalled and self.capture is not None:
                    # Symptom -> evidence, keyed by the tick trace
                    # that breached the watermark. One-shot: a stall
                    # storm yields one clean bundle.
                    self.capture.trigger(
                        "stall", trace_id=tick_tid, step=tick,
                        sink=self.metrics_path,
                    )
            else:
                # A tick with NO decode step (chunked prefill still
                # filling every active slot, or an admission-only
                # tick) has no cadence to measure: feeding its zero
                # to the window would drag the median watermark to 0
                # -- and LEAVING the previous verdict standing would
                # let admission keep shedding on a stall that is
                # already over. The verdict describes the last decode
                # tick only; clear it.
                self._stalled = False
            self._occupancy.append(self.batcher.occupancy)
            if self.capture is not None:
                # Advance (and eventually close) the bounded capture
                # window on the tick axis.
                self.capture.step(tick)
            if tick_cb is not None:
                tick_cb(tick)
            tick += 1

    # -- aggregation ---------------------------------------------------
    def summarize(
        self,
        n_devices: int = 1,
        n_params: Optional[int] = None,
        peak_flops_per_device: Optional[float] = None,
        extra: Optional[dict] = None,
    ) -> dict:
        summary = self.meter.summary(
            n_devices=n_devices, n_params=n_params,
            peak_flops_per_device=peak_flops_per_device,
        )
        m = self.meter
        tenants, slo_violations, violated_tenants = tenant_summary(
            self.scenario, m, self.batcher.spec_by_tenant
        )
        occ = sorted(self._occupancy)
        # The cache layout is part of the run's identity (a paged
        # quantile must never be diffed against a slab one unlabeled);
        # paged engines contribute their hit-rate/pool evidence.
        paged_summary = getattr(self.engine, "paged_summary", None)
        if callable(paged_summary):
            summary.update(paged_summary())
        else:
            summary["kv_layout"] = "slab"
        spec = getattr(self.engine, "spec", None)
        if spec is not None:
            spec_block = spec.spec_summary()
            # The runner's draft_ms is WALL time -- machine noise a
            # byte-identical virtual-clock summary must not carry.
            # Substitute the cost model's modeled charge (a pure
            # function of scenario, seed and the draft fraction).
            spec_block["draft_ms"] = round(
                self.engine.draft_charged_s * 1e3, 3
            )
            summary.update(spec_block)
        summary.update(
            scenario=self.scenario.name,
            seed=self.scenario.seed,
            n_arrivals=len(self.scenario.requests),
            tenants=tenants,
            shed=self.batcher.stats["shed"],
            queued=sum(m.queued_by.values()),
            slo_violations=slo_violations,
            occupancy_mean=(
                sum(occ) / len(occ) if occ else 0.0
            ),
            occupancy_p95=quantile(occ, 0.95),
            stall_events=self.detector.stalls,
            decode_steps=self.batcher.stats["decode_steps"],
            admitted=self.batcher.stats["admitted"],
            virtual_clock=True,
        )
        if extra:
            summary.update(extra)
        if slo_violations and self.capture is not None:
            # SLO breach is the third capture trigger: the run is
            # over (drive()'s finally already closed the bounded
            # window), so no profiler is armed -- there are no future
            # steps to bound or ever close one. The flight dump +
            # device-memory snapshot still preserve the evidence
            # trail, keyed by the first violated tenant's class.
            self.capture.trigger(
                "slo_breach",
                trace_id=trace_id_for("tenant", violated_tenants[0]),
                sink=self.metrics_path,
                arm_profiler=False,
            )
        if self.capture is not None:
            # AFTER the SLO trigger above, so an SLO-breach-only
            # capture is counted -- the summary is the join point the
            # banked rows and the on-disk evidence must agree on.
            summary["captures"] = self.capture.captures
        self.meter.write_summary(summary)
        get_registry().emit_snapshot(sink=self.metrics_path)
        return summary
