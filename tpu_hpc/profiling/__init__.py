from tpu_hpc.profiling.profiler import (  # noqa: F401
    TrainingProfiler,
    device_memory_summary,
    training_profiler,
)
