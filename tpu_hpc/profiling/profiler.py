"""Profiling: jax.profiler traces with schedule windows.

Capability parity with the reference's ``utils/profiling.py``:
``training_profiler`` context manager with wait/warmup/active windowing
(:25-66 -- here ``start_step``/``num_steps``, the same
schedule(wait, warmup, active) idea collapsed to one window), rank-0
(host-0) only trace output (:44-49), TensorBoard-consumable artifacts,
and a memory/summary printer (:69-86).

TPU-native: ``jax.profiler.start_trace`` captures XLA device traces +
HLO cost analysis viewable in TensorBoard/XProf or Perfetto -- the
comm-vs-compute diagnosis workflow the reference docs prescribe
(docs/guide/troubleshooting.md:230-239) works identically: look for
all-reduce/all-gather ops overlapping (good) or serializing (bad) with
the matmul stream. ``StepTraceAnnotation`` marks step boundaries so
XProf computes per-step breakdowns.
"""
from __future__ import annotations

import contextlib
from typing import Iterator, Optional

import jax

from tpu_hpc.logging_ import get_logger


class TrainingProfiler:
    """Step-windowed trace: profile steps [start_step, start_step +
    num_steps) on host 0, skipping warmup/compilation steps (the
    reference's schedule(wait=1, warmup=1, active=3) -- :36-43)."""

    def __init__(
        self,
        log_dir: str = "profiles",
        start_step: int = 3,
        num_steps: int = 5,
        host0_only: bool = True,
    ):
        self.log_dir = log_dir
        self.start_step = start_step
        self.num_steps = num_steps
        self.enabled = not host0_only or jax.process_index() == 0
        self.active = False
        self.logger = get_logger()

    def step(self, step: int) -> None:
        """Call once per training step with the global step index.
        Threshold (not equality) triggered, so chunked loops that
        advance many steps per host iteration still hit the window."""
        if not self.enabled:
            return
        # Open on threshold, not window membership: chunked loops call
        # this only at chunk boundaries, which may skip past the window
        # entirely (e.g. start_step=3 with 20-step epochs -> calls at
        # 0, 20, 40...).
        if not self.active and step >= self.start_step:
            jax.profiler.start_trace(self.log_dir)
            self.active = True
            self.logger.info(
                "profiler: tracing steps %d..%d -> %s",
                step, step + self.num_steps - 1, self.log_dir,
            )
        elif self.active and step >= self.start_step + self.num_steps:
            self.stop()

    def annotate(self, step: int):
        """Step boundary marker for XProf per-step breakdowns; use as
        ``with prof.annotate(step): train_step(...)``."""
        if self.active:
            return jax.profiler.StepTraceAnnotation("train", step_num=step)
        return contextlib.nullcontext()

    def stop(self) -> None:
        """Close an open trace. ``active`` is cleared even when
        ``stop_trace`` itself raises (a full disk mid-write): a stop
        that failed must not make every later stop re-raise on an
        already-dead trace, which is what leaked the open trace the
        finally-guarantee exists for."""
        if self.active:
            try:
                jax.profiler.stop_trace()
            finally:
                self.active = False
            self.logger.info(
                "profiler: trace written to %s (open with TensorBoard "
                "or xprof)", self.log_dir,
            )

    # Context-manager form: ``with TrainingProfiler(...) as prof``
    # guarantees the trace is closed when the loop exhausts inside the
    # window or an exception unwinds through it -- an open
    # jax.profiler.start_trace otherwise leaks for the life of the
    # process (and blocks any later trace from starting).
    def __enter__(self) -> "TrainingProfiler":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()


@contextlib.contextmanager
def training_profiler(
    log_dir: str = "profiles",
    start_step: int = 3,
    num_steps: int = 5,
    host0_only: bool = True,
) -> Iterator[TrainingProfiler]:
    """Context-manager form (parity: utils/profiling.py:25-66); always
    stops the trace on exit, even on error."""
    prof = TrainingProfiler(log_dir, start_step, num_steps, host0_only)
    try:
        yield prof
    finally:
        prof.stop()


def device_memory_summary(
    logger=None,
    devices=None,
    emit: bool = True,
    sink: Optional[str] = None,
) -> Optional[dict]:
    """Per-device HBM usage (the reference's profiler summary table
    analogue, :69-86; here sourced from the runtime's live allocator
    stats rather than a trace).

    Beyond the log lines, the summary lands as telemetry (``emit=True``
    and any device reporting stats): one schema-stamped
    ``device_memory`` event (per-device in_use/peak/limit plus the
    fleet-wide maxima) and an ``hbm_peak_bytes`` registry gauge -- so
    the obs report's memory section and the regress gate see HBM
    high-water marks instead of them scrolling past in a log.
    ``devices`` is injectable for tests (and for summarizing a tier
    subset, e.g. one disagg mesh)."""
    logger = logger or get_logger()
    if devices is None:
        devices = jax.local_devices()
    stats = {}
    for d in devices:
        s = d.memory_stats()
        if not s:
            continue
        in_use = s.get("bytes_in_use", 0)
        limit = s.get("bytes_limit", 0)
        peak = s.get("peak_bytes_in_use", 0)
        stats[str(d)] = {"in_use": in_use, "limit": limit, "peak": peak}
        logger.info(
            "%s | in use %.2f GiB | peak %.2f GiB | limit %.2f GiB",
            d, in_use / 2**30, peak / 2**30, limit / 2**30,
        )
    if not stats:
        return None
    if emit:
        from tpu_hpc.obs import get_bus, get_registry

        peak = max(s["peak"] for s in stats.values())
        get_bus().emit(
            "device_memory",
            sink=sink,
            n_devices=len(stats),
            hbm_peak_bytes=int(peak),
            hbm_in_use_bytes=int(
                max(s["in_use"] for s in stats.values())
            ),
            hbm_limit_bytes=int(
                max(s["limit"] for s in stats.values())
            ),
            per_device=stats,
        )
        get_registry().set_gauge(
            "hbm_peak_bytes", float(peak),
            help="Largest per-device HBM high-water mark (bytes) "
            "reported by the live allocator",
        )
    return stats
