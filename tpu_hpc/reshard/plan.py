"""The reshard planner: decompose source->target redistribution into
memory-bounded steps.

The repo performed the resharding problem twice before this module
existed -- trainer-ckpt -> serving layout (serve/weights.py) and
DP-ckpt -> PP layout (tests/test_pp_llama.py) -- both by handing
orbax/XLA one monolithic "move the bytes" program. GSPMD is allowed to
solve that program by FULL REMATERIALIZATION (it even warns:
"Involuntary full rematerialization ... You probably want to enrich
the sharding annotations"), which means a redistribution whose source
and target shards are both small can transiently demand the whole
array per device -- exactly the failure mode "Memory-efficient array
redistribution through portable collective communication"
(arXiv:2112.01075) decomposes away.

This planner takes any source->target ``NamedSharding`` pair per leaf
(including pairs whose meshes have *different shapes* -- the elastic
resume and disaggregated-serving cases) and emits a
:class:`ReshardPlan`:

* every leaf becomes one :class:`ReshardStep`, classified by what the
  move must do (``noop`` / ``local`` / ``gather`` / ``exchange`` /
  ``transfer`` / ``place``);
* wire bytes are modeled EXACTLY from the shardings' device->index
  maps (bytes each target device needs minus bytes already resident on
  it), not from an op-shape heuristic;
* a step whose conservative transient footprint exceeds
  ``max_inflight_bytes`` is decomposed into chunks along one axis --
  slice, move, write-into-a-preallocated-target -- so no single
  program ever has to materialize more than one chunk beyond the
  source/target shards themselves (the paper's decomposition instead
  of one monolithic gather);
* the plan is introspectable before any byte moves: step table,
  modeled wire/peak-HBM bytes, and per-step compiled programs whose
  collective counts and largest live tensor are checkable with
  :mod:`tpu_hpc.checks.hlo`.

Execution lives in :mod:`tpu_hpc.reshard.execute`; ``plan.execute``
binds the two together and caches compiled programs so a plan built
once (e.g. per prefill bucket in the disaggregated serve tier) replays
with zero recompiles.
"""
from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, List, Optional, Tuple

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

# Step kinds, in "how much does this move" order:
#   noop     -- source placement already equivalent to the target.
#   local    -- placements differ but every target device already
#               holds the bytes it needs (e.g. replicated -> sharded:
#               a local slice, zero wire traffic).
#   gather   -- same mesh, target fully replicated: the one case where
#               a full per-device copy is the REQUIRED residency, not
#               an artifact (lowers to all-gather).
#   exchange -- same mesh, sharded -> sharded with real wire traffic
#               (lowers to all-to-all / collective-permute /
#               bounded gathers; the chunkable case).
#   transfer -- different meshes (elastic resume, cross-tier KV moves);
#               executed with jax.device_put, chunked the same way.
#   place    -- source is host data (numpy / no committed sharding):
#               a straight device_put onto the target.
STEP_KINDS = ("noop", "local", "gather", "exchange", "transfer", "place")


def _norm_index(idx, shape) -> Tuple[Tuple[int, int], ...]:
    """A devices_indices_map entry -> ((start, stop), ...) per dim."""
    out = []
    for sl, dim in zip(idx, shape):
        start = 0 if sl.start is None else int(sl.start)
        stop = dim if sl.stop is None else int(sl.stop)
        out.append((start, stop))
    return tuple(out)


def _vol(box: Tuple[Tuple[int, int], ...]) -> int:
    return math.prod(hi - lo for lo, hi in box)


def _intersect_vol(a, b) -> int:
    v = 1
    for (alo, ahi), (blo, bhi) in zip(a, b):
        d = min(ahi, bhi) - max(alo, blo)
        if d <= 0:
            return 0
        v *= d
    return v


def modeled_wire_bytes(
    shape: Tuple[int, ...], itemsize: int, src, tgt
) -> int:
    """Exact wire model: bytes that must arrive over links, summed per
    target device as (bytes the device needs) - (bytes of that region
    already resident on it). Computed from the shardings' device->index
    maps, so it is correct for any spec pair, any mesh pair, and any
    replication pattern -- no per-op formula to drift."""
    smap = {
        d: _norm_index(idx, shape)
        for d, idx in src.devices_indices_map(shape).items()
    }
    wire = 0
    for d, idx in tgt.devices_indices_map(shape).items():
        box = _norm_index(idx, shape)
        need = _vol(box)
        have = smap.get(d)
        avail = _intersect_vol(have, box) if have is not None else 0
        wire += (need - avail) * itemsize
    return wire


def _spec_without_axis(spec: P, ax: int) -> P:
    """The chunk spec: the target spec with dim ``ax`` unsharded.

    Chunks keep the target layout on every OTHER dim but stay whole
    along the chunk axis, so any chunk length is legal (no divisibility
    coupling between chunk size and the axis extent) and the
    write-back into the preallocated target is a plain
    dynamic-update-slice."""
    entries = list(spec) if spec is not None else []
    while len(entries) <= ax:
        entries.append(None)
    entries[ax] = None
    while entries and entries[-1] is None:
        entries.pop()
    return P(*entries)


@dataclasses.dataclass(frozen=True)
class ChunkPlan:
    """Decomposition of one leaf move along ``axis`` into ``count``
    slices of at most ``size`` rows each."""

    axis: int
    size: int
    count: int


@dataclasses.dataclass(frozen=True)
class ReshardStep:
    """One leaf's move. The serializable summary fields describe the
    step for reports/events; the sharding objects (repr-suppressed)
    are what the executor binds programs to."""

    path: str
    kind: str
    shape: Tuple[int, ...]
    dtype: str
    bytes: int                 # global leaf bytes
    wire_bytes: int            # modeled bytes received over links
    inflight_bytes: int        # modeled peak transient per device
    resident_bytes: int        # largest per-device target residency
    src_resident_bytes: int    # largest per-device source residency
    same_mesh: bool
    chunk: Optional[ChunkPlan]
    bound_met: bool
    src_desc: str
    tgt_desc: str
    src_sharding: Any = dataclasses.field(repr=False, compare=False)
    tgt_sharding: Any = dataclasses.field(repr=False, compare=False)

    def summary(self) -> dict:
        """JSON-safe step record (what the obs event carries)."""
        rec = {
            "path": self.path,
            "kind": self.kind,
            "bytes": self.bytes,
            "wire_bytes": self.wire_bytes,
            "inflight_bytes": self.inflight_bytes,
        }
        if self.chunk is not None:
            rec["chunks"] = self.chunk.count
        return rec

    @property
    def hbm_bound_bytes(self) -> int:
        """The modeled per-device HBM ceiling for this step: the
        larger of the required residencies (source shard, target
        shard) and the allowed transient. A step program whose
        largest live tensor (checks/hlo.max_tensor_bytes over
        ``ReshardPlan.step_hlo``) exceeds this has materialized
        something the plan did not budget -- the full-replica smell
        the bound exists to forbid."""
        return max(
            self.inflight_bytes,
            self.resident_bytes,
            self.src_resident_bytes,
        )


def _describe_sharding(s) -> str:
    if s is None:
        return "host"
    mesh = getattr(s, "mesh", None)
    spec = getattr(s, "spec", None)
    if mesh is not None:
        shape = ",".join(f"{k}={v}" for k, v in mesh.shape.items())
        return f"[{shape}] {spec}"
    return str(s)


def _chunk_offsets(extent: int, size: int) -> List[Tuple[int, int]]:
    return [(a, min(a + size, extent)) for a in range(0, extent, size)]


def _plan_chunks(
    shape: Tuple[int, ...], itemsize: int, max_inflight: int
) -> Tuple[Optional[ChunkPlan], bool]:
    """Pick a chunk axis and size so one chunk's bytes fit the bound.

    Prefers the axis needing the fewest chunks (largest rows-per-chunk
    that still fits). Returns (chunk, bound_met); an unchunkable leaf
    (scalar, or every dim's single row already over the bound) falls
    back to the finest split of the largest dim and reports
    bound_met=False rather than refusing to move the bytes."""
    nbytes = math.prod(shape) * itemsize
    if nbytes <= max_inflight:
        return None, True
    best: Optional[ChunkPlan] = None
    for ax in sorted(
        range(len(shape)), key=lambda a: -shape[a]
    ):
        if shape[ax] < 2:
            continue
        row_bytes = nbytes // shape[ax]
        rows = max(1, max_inflight // max(row_bytes, 1))
        if rows >= shape[ax]:
            continue  # one chunk = whole leaf: no help on this axis
        count = -(-shape[ax] // rows)
        cand = ChunkPlan(axis=ax, size=rows, count=count)
        if row_bytes * rows <= max_inflight:
            return cand, True
        if best is None:
            best = cand  # finest split of the largest dim
    if best is not None:
        return best, False
    return None, False  # nothing to chunk along (scalar-ish leaf)


def plan_step(
    path: str,
    shape: Tuple[int, ...],
    dtype,
    src,
    tgt,
    max_inflight_bytes: Optional[int] = None,
) -> ReshardStep:
    """Classify and (if needed) decompose one leaf's move."""
    import numpy as np

    itemsize = np.dtype(dtype).itemsize
    nbytes = math.prod(shape) * itemsize
    ndim = len(shape)
    resident = max(
        (
            _vol(_norm_index(idx, shape)) * itemsize
            for idx in tgt.devices_indices_map(shape).values()
        ),
        default=nbytes,
    )
    src_resident = 0 if src is None else max(
        (
            _vol(_norm_index(idx, shape)) * itemsize
            for idx in src.devices_indices_map(shape).values()
        ),
        default=nbytes,
    )

    def build(kind, wire, inflight, chunk=None, bound_met=True):
        return ReshardStep(
            path=path, kind=kind, shape=tuple(shape),
            dtype=str(np.dtype(dtype)), bytes=nbytes,
            wire_bytes=wire, inflight_bytes=inflight,
            resident_bytes=resident,
            src_resident_bytes=src_resident,
            same_mesh=(
                src is not None
                and getattr(src, "mesh", None) == getattr(tgt, "mesh", 1)
            ),
            chunk=chunk, bound_met=bound_met,
            src_desc=_describe_sharding(src),
            tgt_desc=_describe_sharding(tgt),
            src_sharding=src, tgt_sharding=tgt,
        )

    if src is None:
        # Host data: device_put stages the full leaf through host
        # memory; the device side only ever holds its target shard.
        return build("place", wire=nbytes, inflight=0)
    if src.is_equivalent_to(tgt, ndim):
        return build("noop", wire=0, inflight=0)
    wire = modeled_wire_bytes(shape, itemsize, src, tgt)
    same_mesh = getattr(src, "mesh", None) == getattr(tgt, "mesh", 1)
    if wire == 0:
        # Every target device already holds what it needs: a local
        # slice/copy, whatever the spec spelling.
        return build("local", wire=0, inflight=0)
    if same_mesh and tgt.is_fully_replicated:
        # The full per-device copy IS the requested residency; an
        # all-gather builds it in place with no transient beyond it.
        return build("gather", wire=wire, inflight=0)
    kind = "exchange" if same_mesh else "transfer"
    # Conservative transient: GSPMD may solve an arbitrary sharded ->
    # sharded move by full rematerialization, and a cross-mesh
    # device_put may gather on some device. The bound forces the
    # chunked decomposition whenever that conservative footprint
    # exceeds it.
    if max_inflight_bytes is None or nbytes <= max_inflight_bytes:
        return build(kind, wire=wire, inflight=nbytes)
    if not (
        isinstance(src, jax.sharding.NamedSharding)
        and isinstance(tgt, jax.sharding.NamedSharding)
    ):
        # The chunked decomposition derives chunk layouts from the
        # PartitionSpecs; a non-named endpoint (committed
        # single-device array, opaque GSPMD sharding) moves whole --
        # honestly over-bound rather than crashing.
        return build(kind, wire=wire, inflight=nbytes, bound_met=False)
    chunk, bound_met = _plan_chunks(shape, itemsize, max_inflight_bytes)
    if chunk is None:
        return build(kind, wire=wire, inflight=nbytes, bound_met=False)
    inflight = min(nbytes, chunk.size * (nbytes // shape[chunk.axis]))
    return build(
        kind, wire=wire, inflight=inflight, chunk=chunk,
        bound_met=bound_met,
    )


@dataclasses.dataclass
class ReshardPlan:
    """An ordered, introspectable redistribution: one step per leaf.

    Aggregates (``wire_bytes``, ``peak_inflight_bytes``) are modeled
    BEFORE execution -- the comm benchmark and the obs events report
    them next to measured time/bytes so model drift is visible.
    ``execute`` (tpu_hpc.reshard.execute) materializes the target tree
    and caches every compiled program inside the plan, so a held plan
    replays with zero recompiles.
    """

    steps: List[ReshardStep]
    treedef: Any
    max_inflight_bytes: Optional[int] = None
    label: Optional[str] = None
    # Provenance of the bound: "planner" when max_inflight_bytes="auto"
    # resolved through the collective planner's cost model
    # (comm/planner.py), None when the caller fixed it by hand.
    inflight_source: Optional[str] = None
    # Planner-predicted wall time of the whole move (auto plans only):
    # per-step launch + wire cost over the modeled fabric tier, next
    # to the measured execution time the reshard span records.
    predicted_cost_s: Optional[float] = None
    _programs: Dict[Any, Any] = dataclasses.field(
        default_factory=dict, repr=False
    )

    # -- modeled aggregates -------------------------------------------
    @property
    def bytes(self) -> int:
        return sum(s.bytes for s in self.steps)

    @property
    def wire_bytes(self) -> int:
        return sum(s.wire_bytes for s in self.steps)

    @property
    def peak_inflight_bytes(self) -> int:
        return max((s.inflight_bytes for s in self.steps), default=0)

    @property
    def chunked_steps(self) -> int:
        return sum(1 for s in self.steps if s.chunk is not None)

    @property
    def bound_met(self) -> bool:
        return all(s.bound_met for s in self.steps)

    @property
    def compiled_program_count(self) -> int:
        """Cached executable programs on this plan -- the number a
        compile-discipline guard should count. The cache also holds
        non-program bookkeeping (the stage-grouping lists); this
        property is the one place that knows which keys are which."""
        return sum(1 for k in self._programs if k[0] != "stages")

    def counts(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for s in self.steps:
            out[s.kind] = out.get(s.kind, 0) + 1
        return out

    def summary(self) -> dict:
        """JSON-safe plan record (the ``reshard_plan`` obs event)."""
        rec = {
            "steps": len(self.steps),
            "bytes": self.bytes,
            "wire_bytes": self.wire_bytes,
            "peak_inflight_bytes": self.peak_inflight_bytes,
            "chunked_steps": self.chunked_steps,
            "max_inflight_bytes": self.max_inflight_bytes,
            "bound_met": self.bound_met,
            "kinds": self.counts(),
        }
        if self.inflight_source is not None:
            rec["inflight_source"] = self.inflight_source
        if self.predicted_cost_s is not None:
            rec["predicted_cost_ms"] = round(
                self.predicted_cost_s * 1e3, 6
            )
        return rec

    def describe(self) -> str:
        """Human-readable step table."""
        lines = [
            f"reshard plan: {len(self.steps)} step(s), "
            f"{self.bytes} B total, {self.wire_bytes} B wire, "
            f"peak inflight {self.peak_inflight_bytes} B"
            + (
                f" (bound {self.max_inflight_bytes} B"
                + ("" if self.bound_met else ", NOT met")
                + ")"
                if self.max_inflight_bytes is not None else ""
            ),
            f"{'kind':9} {'bytes':>12} {'wire':>12} {'inflight':>12} "
            f"{'chunks':>6}  path: src -> tgt",
        ]
        for s in self.steps:
            lines.append(
                f"{s.kind:9} {s.bytes:>12} {s.wire_bytes:>12} "
                f"{s.inflight_bytes:>12} "
                f"{s.chunk.count if s.chunk else 1:>6}  "
                f"{s.path}: {s.src_desc} -> {s.tgt_desc}"
            )
        return "\n".join(lines)

    # -- HLO introspection --------------------------------------------
    def step_hlo(self, index: int, compiled: bool = True) -> List[str]:
        """The XLA program texts step ``index`` will run, for
        verification with :mod:`tpu_hpc.checks.hlo` (collective counts,
        largest-live-tensor bound). Chunked steps lower the SAME
        cached callables the executor runs; unchunked cross-mesh and
        host steps move via ``jax.device_put`` and have no jit-visible
        program (returns [])."""
        from tpu_hpc.reshard import execute as _exec

        return _exec.step_program_texts(self, index, compiled=compiled)

    def execute(
        self, tree, donate: bool = False, copy_noop: bool = False,
        sink=None,
    ):
        """Run the plan on ``tree`` (must match the planned avals);
        returns the tree in the target placement. See
        :func:`tpu_hpc.reshard.execute.execute_plan`."""
        from tpu_hpc.reshard import execute as _exec

        return _exec.execute_plan(
            self, tree, donate=donate, copy_noop=copy_noop, sink=sink
        )


def _leaf_sharding(leaf):
    s = getattr(leaf, "sharding", None)
    if s is None:
        return None
    # Uncommitted single-device jax arrays report a SingleDeviceSharding;
    # treat them like host data (a plain placement, nothing to model).
    if not isinstance(s, jax.sharding.NamedSharding):
        if getattr(s, "num_devices", 1) == 1 and not getattr(
            leaf, "_committed", True
        ):
            return None
    return s


def _planner_for_steps(steps: List[ReshardStep]):
    """The collective planner over the device set this plan touches
    (union of source/target meshes -- the disagg KV hop's two disjoint
    tiers fingerprint as one topology, which is what its cost table
    measures)."""
    from tpu_hpc.comm.planner import Planner

    devs, seen = [], set()
    for s in steps:
        for sh in (s.src_sharding, s.tgt_sharding):
            mesh = getattr(sh, "mesh", None)
            if mesh is None:
                continue
            for d in mesh.devices.flat:
                if id(d) not in seen:
                    seen.add(id(d))
                    devs.append(d)
    return Planner.for_devices(devs or None)


def _predict_cost(steps: List[ReshardStep], planner) -> float:
    """Modeled wall time of the plan: per-step (and per-chunk) launch
    latency plus wire bytes over the step's fabric tier -- the
    exchange-vs-transfer decomposition costed with the same alpha-beta
    terms the planner uses everywhere."""
    from tpu_hpc.comm.planner import tier_cost

    total = 0.0
    # A move on a multi-slice device set pays DCN rates: same-mesh
    # exchanges span the slices too (their collective crosses DCN),
    # and cross-mesh transfers between tiers of one pod do by
    # definition. Single-slice (and the CPU sim) is all ICI.
    tier = "dcn" if planner.fingerprint.n_slices > 1 else "ici"
    for s in steps:
        if s.wire_bytes <= 0:
            continue
        chunks = s.chunk.count if s.chunk else 1
        total += chunks * tier_cost(tier, s.wire_bytes / chunks)
    return total


def plan_reshard(
    tree: Any,
    targets: Any,
    *,
    max_inflight_bytes: "Optional[int | str]" = None,
    label: Optional[str] = None,
) -> ReshardPlan:
    """Plan a source->target redistribution for a whole pytree.

    ``tree`` may hold real arrays or ``ShapeDtypeStruct`` leaves with
    shardings (plan before any byte exists). ``targets`` is a matching
    pytree of ``Sharding`` leaves, or a single ``Sharding`` applied to
    every leaf. ``max_inflight_bytes`` bounds the modeled per-device
    transient of every step (the arXiv:2112.01075 knob): leaves whose
    conservative move exceeds it are decomposed into chunked
    slice->move->write steps. The string ``"auto"`` asks the
    collective planner (tpu_hpc.comm.planner) for the bound: the
    chunk size that amortizes the fabric tier's launch latency, from
    the topology's cost model -- the plan then records
    ``inflight_source="planner"`` and the planner's predicted wall
    time next to its wire-byte model.
    """
    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    if isinstance(targets, jax.sharding.Sharding):
        tgt_flat = [targets] * len(flat)
    else:
        tgt_leaves, tgt_def = jax.tree_util.tree_flatten(
            targets,
            is_leaf=lambda x: isinstance(x, jax.sharding.Sharding),
        )
        if tgt_def != treedef:
            raise ValueError(
                "target sharding tree structure does not match the "
                f"input tree: {tgt_def} vs {treedef}"
            )
        tgt_flat = tgt_leaves
    from tpu_hpc.parallel.plans import _path_str

    def build_steps(bound: Optional[int]) -> List[ReshardStep]:
        steps = []
        for (path, leaf), tgt in zip(flat, tgt_flat):
            if not isinstance(tgt, jax.sharding.Sharding):
                raise TypeError(
                    f"target for {_path_str(path)} is "
                    f"{type(tgt).__name__}, not a Sharding"
                )
            steps.append(plan_step(
                _path_str(path),
                tuple(leaf.shape),
                leaf.dtype,
                _leaf_sharding(leaf),
                tgt,
                max_inflight_bytes=bound,
            ))
        return steps

    inflight_source = None
    predicted = None
    if max_inflight_bytes == "auto":
        # Two passes: classify unbounded first (kinds and wire bytes
        # do not depend on the bound), ask the planner for the chunk
        # size that amortizes the relevant tier's launch latency, then
        # re-plan under it. Planning is host-side arithmetic; the
        # second pass costs microseconds.
        steps0 = build_steps(None)
        planner = _planner_for_steps(steps0)
        movers = [s for s in steps0 if s.wire_bytes > 0]
        if movers:
            max_inflight_bytes = planner.chunk_bytes(
                max(s.bytes for s in movers)
            )
        else:
            max_inflight_bytes = None  # nothing moves: no bound needed
        inflight_source = "planner"
        steps = build_steps(max_inflight_bytes)
        predicted = _predict_cost(steps, planner)
    else:
        if max_inflight_bytes is not None and not hasattr(
            max_inflight_bytes, "__index__"
        ):
            raise TypeError(
                f"max_inflight_bytes must be an int, None, or "
                f"'auto'; got {max_inflight_bytes!r}"
            )
        steps = build_steps(max_inflight_bytes)
    return ReshardPlan(
        steps=steps, treedef=treedef,
        max_inflight_bytes=max_inflight_bytes, label=label,
        inflight_source=inflight_source, predicted_cost_s=predicted,
    )
