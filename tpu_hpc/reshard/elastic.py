"""Elastic resume: checkpoint topology sidecars + cross-topology restore.

A checkpoint written on one pod shape and restored on another is the
resilience story's missing half: the supervisor can relaunch a
preempted run, but only onto the SAME mesh. This module closes that
gap for ``ckpt.CheckpointManager``:

* every save writes a tiny JSON sidecar (``.tpu_hpc_meta/<step>.json``)
  recording the mesh axes and per-leaf shardings the state was written
  with -- the source topology, which orbax's array metadata alone does
  not surface to the restore path;
* ``restore_latest`` compares the sidecar against the live template's
  mesh; when the topologies differ it restores INTO THE SOURCE LAYOUT
  (rebuilt over the live devices, so no implicit cross-layout movement
  hides inside orbax) and then runs an explicit
  :mod:`tpu_hpc.reshard` plan onto the live shardings -- bounded,
  span-bracketed, and reported as an ``elastic_restore`` event;
* when a restore fails STRUCTURALLY (wrong model/shape on relaunch --
  every step fails, unlike a torn newest write), the sidecar lets the
  error name the source vs. live topology instead of surfacing a
  generic orbax traceback: :class:`TopologyMismatchError`.
"""
from __future__ import annotations

import json
import math
import os
from typing import Any, Dict, List, Optional, Tuple

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

SIDECAR_DIR = ".tpu_hpc_meta"


class TopologyMismatchError(ValueError):
    """A checkpoint exists but cannot be restored against the live
    state: the topologies/shapes are structurally incompatible (not a
    torn write, which only fails the newest step). The message names
    the source and live topology; for a legitimate pod-shape change
    the elastic-resume path (docs/guide/resharding.md) handles the
    move automatically -- this error means the trees themselves
    disagree."""


def _spec_to_json(spec) -> list:
    out: List[Any] = []
    for entry in tuple(spec):
        if entry is None or isinstance(entry, str):
            out.append(entry)
        else:
            out.append(list(entry))
    return out


def _spec_from_json(data) -> P:
    entries = []
    for entry in data:
        if entry is None or isinstance(entry, str):
            entries.append(entry)
        else:
            entries.append(tuple(entry))
    return P(*entries)


def _path_leaves(tree) -> List[Tuple[str, Any]]:
    from tpu_hpc.parallel.plans import _path_str

    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    return [(_path_str(path), leaf) for path, leaf in flat]


def topology_of(state: Any) -> Optional[dict]:
    """The topology record for a state tree: mesh axes plus per-leaf
    shape/dtype/spec. None when no leaf carries a ``NamedSharding``
    (host-local trees -- nothing cross-topology to record; the
    sidecar is still written for such trees, mesh-less, so the
    integrity checksums have somewhere to live)."""
    mesh = None
    leaves: Dict[str, dict] = {}
    for path, leaf in _path_leaves(state):
        sharding = getattr(leaf, "sharding", None)
        rec = {
            "shape": [int(d) for d in getattr(leaf, "shape", ())],
            "dtype": str(getattr(leaf, "dtype", "")),
        }
        if isinstance(sharding, NamedSharding):
            if mesh is None:
                mesh = sharding.mesh
            rec["spec"] = _spec_to_json(sharding.spec)
        leaves[path] = rec
    if mesh is None:
        return None
    return {
        "mesh": {k: int(v) for k, v in mesh.shape.items()},
        "device_count": int(mesh.size),
        "leaves": leaves,
    }


def _leaves_only(state: Any) -> dict:
    """The mesh-less sidecar record for host-local trees: per-leaf
    shape/dtype so structural mismatches still get the typed error,
    no ``mesh`` key so the elastic path never engages."""
    return {
        "leaves": {
            path: {
                "shape": [int(d) for d in getattr(leaf, "shape", ())],
                "dtype": str(getattr(leaf, "dtype", "")),
            }
            for path, leaf in _path_leaves(state)
        },
    }


def _sidecar_path(directory: str, step: int) -> str:
    return os.path.join(directory, SIDECAR_DIR, f"{int(step)}.json")


def _history_path(directory: str) -> str:
    # Not ``<step>.json``-shaped, so the per-step sidecar scan never
    # mistakes it for a checkpoint record.
    return os.path.join(directory, SIDECAR_DIR, "topology_history.json")


def append_topology_history(
    directory: str, step: int, topology: Optional[dict],
    reason: str = "save",
) -> None:
    """Record that the run was on ``topology`` at ``step`` (host 0
    only). The history file is the in-place morph audit: a run that
    shrank and grew back writes one entry per transition (plus one per
    save), so "what shape was the run in at step N" is answerable
    after the fact without replaying the event log. Entries are
    pruned WITH their checkpoint steps (:func:`prune_sidecars`) --
    morph entries (``reason != "save"``) are dropped once they fall
    before the oldest retained checkpoint (no retained step could
    restore into a world where they matter)."""
    if jax.process_index() != 0:
        return
    mesh = (topology or {}).get("mesh")
    entry = {
        "step": int(step),
        "mesh": dict(mesh) if mesh else None,
        "device_count": (topology or {}).get("device_count"),
        "reason": str(reason),
    }
    path = _history_path(directory)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    history = read_topology_history(directory)
    history.append(entry)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(history, f)
    os.replace(tmp, path)


def read_topology_history(directory: str) -> List[dict]:
    """The topology-history entries, in append order (empty for
    pre-history checkpoints)."""
    try:
        with open(_history_path(directory)) as f:
            data = json.load(f)
        return list(data) if isinstance(data, list) else []
    except (OSError, ValueError):
        return []


def write_sidecar(
    directory: str, step: int, state: Any,
    extra: Optional[dict] = None,
) -> Optional[str]:
    """Record ``state``'s topology for checkpoint ``step`` (host 0
    only; other hosts return None). A state with no NamedSharding
    leaves writes a mesh-less record (leaf shapes/dtypes only): the
    elastic path never engages for it, but the integrity checksums
    (``extra={"checksums": ...}``, ckpt.integrity) and the typed
    structural-mismatch error still work."""
    if jax.process_index() != 0:
        return None
    topo = topology_of(state)
    if topo is None:
        topo = _leaves_only(state)
    topo["step"] = int(step)
    if extra:
        topo.update(extra)
    path = _sidecar_path(directory, step)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(topo, f)
    os.replace(tmp, path)
    append_topology_history(directory, step, topo, reason="save")
    return path


def stash_sidecar(
    directory: str, step: int, suffix: str
) -> Optional[str]:
    """Rename one step's sidecar aside (``<step>.json.<suffix>``,
    uniqued) -- the quarantine path. A renamed-aside step dir must
    not leave a live-looking topology record, but its save-time
    checksums are evidence worth keeping: they are what can later
    prove (or disprove) the corruption. The suffixed name no longer
    ends in ``.json``, so sidecar pruning leaves it alone."""
    src = _sidecar_path(directory, step)
    if not os.path.exists(src):
        return None
    dst, k = f"{src}.{suffix}", 0
    while os.path.exists(dst):
        k += 1
        dst = f"{src}.{suffix}.{k}"
    try:
        os.rename(src, dst)
        return dst
    except OSError:
        return None


def read_sidecar(directory: str, step: int) -> Optional[dict]:
    """The topology record written with checkpoint ``step``, or None
    (pre-sidecar checkpoints restore exactly as before)."""
    try:
        with open(_sidecar_path(directory, step)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def prune_sidecars(directory: str, keep_steps) -> None:
    """Drop sidecars whose checkpoint orbax has garbage-collected,
    and prune the topology-history file to match: ``save`` entries
    for GC'd steps go with their sidecars, morph entries older than
    the oldest retained checkpoint go too (a morph-history file on a
    long run would otherwise grow without bound)."""
    meta = os.path.join(directory, SIDECAR_DIR)
    try:
        names = os.listdir(meta)
    except OSError:
        return
    steps = {int(s) for s in keep_steps}
    keep = {f"{s}.json" for s in steps}
    history = os.path.basename(_history_path(directory))
    for name in names:
        if name == history:
            continue
        if name.endswith(".json") and name not in keep:
            try:
                os.remove(os.path.join(meta, name))
            except OSError:
                pass
    old = read_topology_history(directory)
    if not old:
        return
    floor = min(steps) if steps else 0
    kept = [
        e for e in old
        if (
            int(e.get("step", -1)) in steps
            if e.get("reason") == "save"
            else int(e.get("step", -1)) >= floor
        )
    ]
    if kept != old:
        path = _history_path(directory)
        tmp = path + ".tmp"
        try:
            with open(tmp, "w") as f:
                json.dump(kept, f)
            os.replace(tmp, path)
        except OSError:
            pass


def live_mesh_of(template: Any):
    """The mesh the template's first NamedSharding leaf lives on."""
    for _, leaf in _path_leaves(template):
        sharding = getattr(leaf, "sharding", None)
        if isinstance(sharding, NamedSharding):
            return sharding.mesh
    return None


def needs_reshard(meta: dict, template: Any) -> bool:
    """True when the checkpoint's mesh axes differ from the live
    template's -- the cross-topology case the explicit reshard path
    exists for. Same-mesh spec differences stay on the direct restore
    (orbax lands bytes straight into the template's shardings)."""
    if not meta.get("mesh"):
        # Mesh-less sidecar (host-local save, or a checksums-only
        # record): nothing cross-topology to reconcile.
        return False
    mesh = live_mesh_of(template)
    if mesh is None:
        return False
    live = {k: int(v) for k, v in mesh.shape.items()}
    return meta.get("mesh") != live


def describe_mismatch(meta: dict, template: Any) -> Optional[str]:
    """Human-readable structural difference between a sidecar and the
    live template, or None when the structures agree (the failure was
    not topological)."""
    saved = meta.get("leaves", {})
    live = {
        path: [int(d) for d in getattr(leaf, "shape", ())]
        for path, leaf in _path_leaves(template)
    }
    missing = sorted(set(saved) - set(live))
    extra = sorted(set(live) - set(saved))
    if missing or extra:
        return (
            f"tree structure differs: {len(missing)} leaf/leaves only "
            f"in the checkpoint (first: {missing[:3]}), {len(extra)} "
            f"only in the live state (first: {extra[:3]})"
        )
    for path, shape in live.items():
        got = saved[path].get("shape")
        if got != shape:
            return (
                f"leaf {path!r} has shape {got} in the checkpoint but "
                f"{shape} in the live state (wrong model config?)"
            )
    return None


def source_template(meta: dict, template: Any) -> Optional[Any]:
    """The checkpoint's own layout, rebuilt over the live devices: a
    template whose leaves carry the SOURCE shardings, so the restore
    lands bytes exactly as written and the explicit reshard plan owns
    every cross-layout move.

    None when the source mesh cannot be built from the live process's
    devices (a grown-then-shrunk world where the source needed more
    chips than exist now) -- the caller falls back to the direct
    orbax restore, which handles that case opaquely but correctly.
    Raises :class:`TopologyMismatchError` when the tree structure
    itself disagrees (a reshard cannot fix a wrong model).
    """
    from tpu_hpc.runtime import MeshSpec, build_mesh

    mismatch = describe_mismatch(meta, template)
    if mismatch is not None:
        raise TopologyMismatchError(
            f"checkpoint (mesh {meta.get('mesh')}) is structurally "
            f"incompatible with the live state: {mismatch}"
        )
    axes = meta.get("mesh") or {}
    total = math.prod(axes.values()) if axes else 0
    devices = jax.devices()
    if total < 1 or total > len(devices):
        return None
    src_mesh = build_mesh(
        MeshSpec(axes=dict(axes)), devices=devices[:total]
    )
    saved = meta["leaves"]

    def leaf_template(path, leaf):
        rec = saved[path]
        spec = rec.get("spec")
        sharding = NamedSharding(
            src_mesh,
            _spec_from_json(spec) if spec is not None else P(),
        )
        # LIVE dtype, deliberately: orbax casts into the template's
        # dtype at restore time, so a dtype switch on relaunch (the
        # fp32->bf16 moments unlock) behaves identically on the
        # elastic path and the direct path -- the reshard then moves
        # already-cast bytes. Dtype differences are a legal config
        # change, never a structural mismatch.
        return jax.ShapeDtypeStruct(
            tuple(leaf.shape), leaf.dtype, sharding=sharding
        )

    flat, treedef = jax.tree_util.tree_flatten_with_path(template)
    from tpu_hpc.parallel.plans import _path_str

    leaves = [
        leaf_template(_path_str(path), leaf) for path, leaf in flat
    ]
    return jax.tree_util.tree_unflatten(treedef, leaves)


def target_shardings(template: Any) -> Any:
    """The live template's shardings, as a matching pytree -- the
    reshard targets for the elastic path."""
    return jax.tree.map(lambda leaf: leaf.sharding, template)
