"""tpu_hpc.reshard -- memory-bounded cross-topology resharding.

The general source->target redistribution engine (arXiv:2112.01075):
plan any ``NamedSharding`` -> ``NamedSharding`` move -- including
across meshes of different shapes -- as an introspectable chain of
bounded steps, then execute it with cached compiled programs.

  plan.py     the planner: exact wire-byte model, step taxonomy,
              chunked decomposition under ``max_inflight_bytes``.
  execute.py  the executor: packed identity programs, device_put
              transfers, chunk slice->move->write assembly; obs spans,
              the peak-HBM gauge, ``reshard_plan`` events.
  elastic.py  checkpoint topology sidecars + the elastic-resume
              restore path (ckpt.restore_latest routes through it when
              a checkpoint's topology differs from the live mesh).

Consumers in-tree: serve/weights.py (trainer ckpt -> serving layout),
serve/disagg.py (prefill-tier KV blocks -> decode tier),
ckpt/checkpoint.py (resume onto a different pod shape), and the
legacy DP-ckpt -> PP placement in tests/test_pp_llama.py.
"""
from tpu_hpc.reshard.elastic import (  # noqa: F401
    TopologyMismatchError,
    read_sidecar,
    topology_of,
    write_sidecar,
)
from tpu_hpc.reshard.execute import apply, execute_plan  # noqa: F401
from tpu_hpc.reshard.plan import (  # noqa: F401
    ChunkPlan,
    ReshardPlan,
    ReshardStep,
    modeled_wire_bytes,
    plan_reshard,
)

__all__ = [
    "ChunkPlan",
    "ReshardPlan",
    "ReshardStep",
    "TopologyMismatchError",
    "apply",
    "execute_plan",
    "modeled_wire_bytes",
    "plan_reshard",
    "read_sidecar",
    "topology_of",
    "write_sidecar",
]
