"""The reshard executor: run a :class:`~tpu_hpc.reshard.plan.ReshardPlan`.

Execution discipline, per step kind (plan.py documents the taxonomy):

* same-mesh unchunked steps are PACKED into joint jitted-identity
  programs (one dispatch moves many leaves), greedily bounded so the
  summed conservative transient of each program stays under the plan's
  ``max_inflight_bytes``;
* cross-mesh (``transfer``) and host (``place``) steps go through
  ``jax.device_put``, batched the same bounded way;
* chunked steps run the paper's decomposition: preallocate the target,
  then per chunk slice -> move -> dynamic-update-slice, each chunk its
  own program so XLA can never fuse the transient footprints together.

Every compiled program is cached INSIDE the plan, keyed by step/chunk,
so a held plan replays with zero recompiles -- the property the
disaggregated serve tier's per-bucket KV plans and the elastic restore
path rely on.

Observability: each execution is bracketed in a ``reshard`` span, emits
one schema-stamped ``reshard_plan`` event -- modeled wire/peak bytes
next to ``measured_bytes``, the payload the executor actually moved,
summed from the OUTPUT arrays at runtime (an accounting cross-check on
the plan, not a hardware wire counter) -- sets the
``reshard_inflight_bytes`` gauge around every stage and the
``reshard_peak_hbm_bytes`` gauge to the execution's modeled per-device
peak (transient + target residency), and counts
``reshard_wire_bytes_total``.
"""
from __future__ import annotations

from typing import Any, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding

from tpu_hpc.obs import get_bus, get_registry, span
from tpu_hpc.reshard.plan import (
    ReshardPlan,
    ReshardStep,
    _spec_without_axis,
    _chunk_offsets,
)


def _chunk_src_sharding(step: ReshardStep):
    ax = step.chunk.axis
    src = step.src_sharding
    return NamedSharding(src.mesh, _spec_without_axis(src.spec, ax))


def _chunk_tgt_sharding(step: ReshardStep):
    ax = step.chunk.axis
    tgt = step.tgt_sharding
    return NamedSharding(tgt.mesh, _spec_without_axis(tgt.spec, ax))


def _slice_program(plan: ReshardPlan, idx: int, a: int, b: int):
    """Slice rows [a, b) of the source and land them in the chunk
    layout (target layout on every other dim, whole along the chunk
    axis). Same-mesh plans reshard in the same program; cross-mesh
    plans keep the chunk on the source mesh (minus the chunk axis) and
    let device_put carry it across.

    The offset is deliberately STATIC (one program per chunk, not per
    chunk length): a traced-offset dynamic_slice along a sharded dim
    forces GSPMD to rematerialize the FULL operand per device (it
    cannot know at compile time which shards contribute), which
    silently voids the max_inflight_bytes contract -- measured on the
    sim mesh: the traced-offset program's largest live tensor is the
    whole array. O(chunks) compiles are the price of the bound, and
    they amortize: programs are cached on the plan."""
    step = plan.steps[idx]
    key = ("slice", idx, a)
    if key not in plan._programs:
        ax = step.chunk.axis
        out = (
            _chunk_tgt_sharding(step) if step.same_mesh
            else _chunk_src_sharding(step)
        )
        plan._programs[key] = jax.jit(
            lambda x: jax.lax.slice_in_dim(x, a, b, axis=ax),
            out_shardings=out,
        )
    return plan._programs[key]


def _init_program(plan: ReshardPlan, idx: int):
    step = plan.steps[idx]
    key = ("init", idx)
    if key not in plan._programs:
        dtype = np.dtype(step.dtype)
        shape = step.shape
        plan._programs[key] = jax.jit(
            lambda: jnp.zeros(shape, dtype),
            out_shardings=step.tgt_sharding,
        )
    return plan._programs[key]


def _write_program(plan: ReshardPlan, idx: int, a: int):
    """Write one landed chunk into the preallocated target at STATIC
    offset ``a`` (static for the same GSPMD-rematerialization reason
    as the slice side). The buffer is donated -- the target is built
    in place, so the assembly never holds two copies of it."""
    step = plan.steps[idx]
    key = ("write", idx, a)
    if key not in plan._programs:
        ax = step.chunk.axis
        plan._programs[key] = jax.jit(
            lambda buf, c: jax.lax.dynamic_update_slice_in_dim(
                buf, c, a, axis=ax
            ),
            donate_argnums=(0,),
            out_shardings=step.tgt_sharding,
        )
    return plan._programs[key]


def _run_chunked(plan: ReshardPlan, idx: int, leaf):
    step = plan.steps[idx]
    extent = step.shape[step.chunk.axis]
    buf = _init_program(plan, idx)()
    for a, b in _chunk_offsets(extent, step.chunk.size):
        chunk = _slice_program(plan, idx, a, b)(leaf)
        if not step.same_mesh:
            chunk = jax.device_put(chunk, _chunk_tgt_sharding(step))
        buf = _write_program(plan, idx, a)(buf, chunk)
    return buf


def _stages(
    plan: ReshardPlan, copy_noop: bool = False
) -> List[Tuple[str, Any]]:
    """Group steps into execution stages (cached on the plan):

    ``("pass", i)`` noop passthrough; ``("chunked", i)`` one chunked
    step; ``("jit", (indices...))`` packed same-mesh identity program;
    ``("dput", (indices...))`` packed device_put batch. Packs are
    bounded by the plan's ``max_inflight_bytes`` over the summed
    conservative transients. ``copy_noop=True`` routes noop leaves
    through the identity program too (fresh buffers instead of
    aliasing the input -- safe since a noop's source assignment equals
    the target's, whatever mesh spelled it)."""
    key = ("stages", copy_noop)
    if key in plan._programs:
        return plan._programs[key]
    bound = plan.max_inflight_bytes
    stages: List[Tuple[str, Any]] = []
    jit_groups = {}   # target mesh -> (indices, inflight sum)
    dput: Tuple[list, int] = ([], 0)

    def flush_jit(gkey):
        idxs, _ = jit_groups.pop(gkey)
        if idxs:
            stages.append(("jit", tuple(idxs)))

    def flush_dput():
        nonlocal dput
        if dput[0]:
            stages.append(("dput", tuple(dput[0])))
        dput = ([], 0)

    def pack_jit(i, step):
        gkey = step.tgt_sharding.mesh
        idxs, acc = jit_groups.get(gkey, ([], 0))
        if bound is not None and idxs and (
            acc + step.inflight_bytes > bound
        ):
            jit_groups[gkey] = (idxs, acc)
            flush_jit(gkey)
            idxs, acc = [], 0
        idxs.append(i)
        jit_groups[gkey] = (idxs, acc + step.inflight_bytes)

    for i, step in enumerate(plan.steps):
        if step.kind == "noop":
            if copy_noop:
                pack_jit(i, step)
            else:
                stages.append(("pass", i))
        elif step.chunk is not None:
            stages.append(("chunked", i))
        elif step.same_mesh or step.kind == "place":
            # "place" (host/uncommitted source) rides the identity
            # program too: jit commits the input AND guarantees fresh
            # output buffers, where device_put may alias a resident
            # single-device buffer into the output.
            pack_jit(i, step)
        else:
            idxs, acc = dput
            if bound is not None and idxs and (
                acc + step.inflight_bytes > bound
            ):
                flush_dput()
                idxs, acc = dput
            idxs.append(i)
            dput = (idxs, acc + step.inflight_bytes)
    for gkey in list(jit_groups):
        flush_jit(gkey)
    flush_dput()
    plan._programs[key] = stages
    return stages


def _may_alias(step: ReshardStep) -> bool:
    """Whether a device_put for this step can return buffers shared
    with the source: only possible when source and target device sets
    overlap (jax reuses resident per-device buffers)."""
    src = step.src_sharding
    if src is None:
        return True  # uncommitted single-device source: resident
    return bool(
        set(src.device_set) & set(step.tgt_sharding.device_set)
    )


def _fresh_copy_program(plan: ReshardPlan, idx: int):
    """Same-mesh identity on the TARGET sharding: jit outputs never
    alias non-donated inputs, so this severs any device_put aliasing."""
    key = ("fresh", idx)
    if key not in plan._programs:
        plan._programs[key] = jax.jit(
            lambda t: t, out_shardings=plan.steps[idx].tgt_sharding
        )
    return plan._programs[key]


def _jit_stage_program(plan: ReshardPlan, idxs, donate: bool):
    key = ("jit", idxs, donate)
    if key not in plan._programs:
        out = tuple(plan.steps[i].tgt_sharding for i in idxs)
        # Host-sourced ("place") operands are not device buffers;
        # donating them only produces XLA warnings, so they are
        # excluded from the donation set.
        donatable = tuple(
            k for k, i in enumerate(idxs)
            if plan.steps[i].kind != "place"
        ) if donate else ()
        plan._programs[key] = jax.jit(
            lambda *xs: xs,
            out_shardings=out,
            donate_argnums=donatable,
        )
    return plan._programs[key]


def _stage_inflight(plan: ReshardPlan, stage) -> int:
    kind, payload = stage
    if kind in ("pass",):
        return 0
    if kind == "chunked":
        return plan.steps[payload].inflight_bytes
    return sum(plan.steps[i].inflight_bytes for i in payload)


def step_program_texts(
    plan: ReshardPlan, index: int, compiled: bool = True
) -> List[str]:
    """The XLA program texts step ``index`` runs, lowered from
    abstract operands -- the introspection hook behind
    ``ReshardPlan.step_hlo``.

    Chunked steps lower THE SAME cached jitted callables the executor
    runs (``_init_program``/``_slice_program``/``_write_program``,
    donation flags included), so the bound-checked HLO cannot drift
    from the executed programs. Unchunked same-mesh steps lower a
    single-leaf identity (execution may pack several leaves into one
    program; the per-leaf collectives are the same, the packing is
    reported by the peak-HBM gauge). Cross-mesh hops (device_put)
    have no jit-visible program and contribute no text."""
    step = plan.steps[index]

    def text(jfn, *avals):
        low = jfn.lower(*avals)
        return (low.compile().as_text() if compiled else low.as_text())

    if step.kind == "noop":
        return []
    dtype = np.dtype(step.dtype)
    src_aval = jax.ShapeDtypeStruct(
        step.shape, dtype, sharding=step.src_sharding
    ) if step.src_sharding is not None else None
    tgt_aval = jax.ShapeDtypeStruct(
        step.shape, dtype, sharding=step.tgt_sharding
    )
    if step.chunk is None:
        if not step.same_mesh or step.src_sharding is None:
            return []  # plain device_put
        return [text(
            jax.jit(lambda x: x, out_shardings=step.tgt_sharding),
            src_aval,
        )]
    ax = step.chunk.axis
    texts = [text(_init_program(plan, index))]
    chunk_tgt = _chunk_tgt_sharding(step)
    for a, b in _chunk_offsets(step.shape[ax], step.chunk.size):
        if step.src_sharding is not None:
            texts.append(
                text(_slice_program(plan, index, a, b), src_aval)
            )
        cshape = list(step.shape)
        cshape[ax] = b - a
        texts.append(text(
            _write_program(plan, index, a),
            tgt_aval,
            jax.ShapeDtypeStruct(tuple(cshape), dtype,
                                 sharding=chunk_tgt),
        ))
    return texts


def execute_plan(
    plan: ReshardPlan,
    tree: Any,
    *,
    donate: bool = False,
    copy_noop: bool = False,
    sink: Optional[str] = None,
) -> Any:
    """Execute ``plan`` on ``tree``; returns the target-placed tree.

    ``donate=True`` transfers ownership of the source buffers: packed
    identity programs donate their inputs, chunked sources and
    disjoint-device transfers are explicitly deleted as soon as their
    stage's target materializes, and the remaining (possibly-aliased
    overlapping-set) sources are dropped by reference -- the caller
    must not touch the input tree afterwards. Leave False when the
    caller keeps using the input. ``copy_noop=True`` additionally
    gives already-placed (noop) leaves fresh buffers instead of
    aliasing the input -- the serve weight placement's fresh-buffer
    contract.
    """
    flat, treedef = jax.tree_util.tree_flatten(tree)
    if treedef != plan.treedef:
        raise ValueError(
            f"tree structure does not match the plan: {treedef} vs "
            f"{plan.treedef}"
        )
    if len(flat) != len(plan.steps):
        raise ValueError(
            f"{len(flat)} leaves vs {len(plan.steps)} planned steps"
        )
    for leaf, step in zip(flat, plan.steps):
        if tuple(leaf.shape) != step.shape or (
            np.dtype(leaf.dtype) != np.dtype(step.dtype)
        ):
            raise ValueError(
                f"leaf {step.path}: {tuple(leaf.shape)}/{leaf.dtype} "
                f"does not match the planned {step.shape}/{step.dtype}"
            )
    out: List[Any] = [None] * len(flat)
    reg = get_registry()
    stages = _stages(plan, copy_noop)
    # Modeled per-device peak while a STAGE runs: packed stages move
    # many leaves in one program, so the footprint is the per-stage
    # SUM of (source shard still live + target shard being built +
    # transient), not the largest single step.
    def _stage_hbm(stage):
        kind, payload = stage
        idxs = (payload,) if kind in ("pass", "chunked") else payload
        return sum(
            plan.steps[i].src_resident_bytes
            + plan.steps[i].resident_bytes
            + plan.steps[i].inflight_bytes
            for i in idxs
        )

    peak_hbm = max((_stage_hbm(s) for s in stages), default=0)
    moved = 0

    def release(indices, chunked=False):
        # donate=True ownership transfer for the paths jit donation
        # cannot cover (device_put transfers, chunked assemblies):
        # drop the source buffers as soon as the stage's target is
        # resident, so the peak never holds both full layouts.
        #
        # Deleting is only safe when the target CANNOT share buffers
        # with the source: chunked assemblies qualify always (the
        # source is read by non-donating jit slice programs, whose
        # outputs are fresh), a plain device_put only when the source
        # and target device sets are disjoint -- jax reuses resident
        # per-device buffers for overlapping sets (a replicated scalar
        # moved onto a sub-mesh comes back aliased), and deleting the
        # source would kill the output. Overlapping-set sources just
        # drop our reference and free by refcount.
        if not donate:
            return
        for i in indices:
            arr = flat[i]
            step = plan.steps[i]
            flat[i] = None
            if not isinstance(arr, jax.Array) or arr is out[i]:
                continue
            if not chunked:
                src = step.src_sharding
                if src is None or (
                    set(src.device_set) & set(
                        step.tgt_sharding.device_set
                    )
                ):
                    continue
            try:
                arr.delete()
            except RuntimeError:
                pass  # already deleted (duplicate-leaf trees)

    with span("reshard", sink=sink, n=len(plan.steps),
              hist="reshard_execute_s"):
        for stage in stages:
            kind, payload = stage
            reg.set_gauge(
                "reshard_inflight_bytes", _stage_inflight(plan, stage)
            )
            if kind == "pass":
                out[payload] = flat[payload]
            elif kind == "chunked":
                out[payload] = _run_chunked(plan, payload, flat[payload])
                moved += out[payload].nbytes
                release((payload,), chunked=True)
            elif kind == "jit":
                prog = _jit_stage_program(plan, payload, donate)
                results = prog(*(flat[i] for i in payload))
                for i, r in zip(payload, results):
                    out[i] = r
                    moved += r.nbytes
            else:  # dput
                arrs = [flat[i] for i in payload]
                shardings = [
                    plan.steps[i].tgt_sharding for i in payload
                ]
                results = jax.device_put(arrs, shardings)
                for i, r in zip(payload, results):
                    if copy_noop and _may_alias(plan.steps[i]):
                        # Fresh-buffer contract on the device_put
                        # path: overlapping-device-set transfers may
                        # hand back buffers aliased with the source;
                        # a same-mesh identity copy on the TARGET
                        # severs the aliasing.
                        r = _fresh_copy_program(plan, i)(r)
                    out[i] = r
                    moved += r.nbytes
                del arrs
                release(payload)
        reg.set_gauge("reshard_inflight_bytes", 0)
    reg.set_gauge("reshard_peak_hbm_bytes", peak_hbm)
    reg.inc("reshard_wire_bytes_total", plan.wire_bytes)
    reg.inc("reshard_executions_total")
    get_bus().emit(
        "reshard_plan",
        sink=sink,
        label=plan.label,
        measured_bytes=moved,
        **plan.summary(),
    )
    return jax.tree_util.tree_unflatten(plan.treedef, out)


def apply(
    tree: Any,
    targets: Any,
    *,
    max_inflight_bytes: Optional[int] = None,
    donate: bool = False,
    copy_noop: bool = False,
    label: Optional[str] = None,
    sink: Optional[str] = None,
) -> Any:
    """Plan + execute in one call: reshard ``tree`` onto ``targets``
    (a matching pytree of shardings, or one sharding for every leaf).
    For repeated moves of same-shaped trees build the plan once with
    :func:`~tpu_hpc.reshard.plan.plan_reshard` and call
    ``plan.execute`` -- the compiled programs are cached on the plan."""
    from tpu_hpc.reshard.plan import plan_reshard

    plan = plan_reshard(
        tree, targets, max_inflight_bytes=max_inflight_bytes,
        label=label,
    )
    return execute_plan(
        plan, tree, donate=donate, copy_noop=copy_noop, sink=sink
    )
