"""Llama-2 transformer: the north-star LLM workload.

Capability parity with the reference's Llama-2 implementation
(fsdp_tp/llama2_model.py, identical copy in scripts/06_hybrid_parallelism/):
ModelArgs surface (:12-27), RoPE (:30-100), GQA via grouped KV heads
(:103-112), RMSNorm (:115-142), causal attention (:145-228), SwiGLU
FeedForward with the 2/3 rule + multiple_of rounding (:231-272),
depth-scaled residual-output init (:275-345), trunc-normal output head
(:348-448).

TPU-first design (not a translation):
  * flax.linen functional modules; params are an explicit pytree so TP
    is a PartitionSpec plan over param paths (parallel/tp.py), not a
    module-wrapping pass.
  * bf16 compute / fp32 params + fp32 RoPE and softmax; matmuls land on
    the MXU in bf16, reductions stay fp32.
  * RoPE carried as real cos/sin pairs (complex64 never touches the
    TPU vector unit well); computed at trace time, constant-folded.
  * separate wq/wk/wv projections (same deliberate choice as the
    reference's ViT :93-110 -- head-dim sharding stays clean under TP).
  * an optional ``constrain`` hook threads activation sharding
    constraints (Megatron-SP sequence sharding) through the block
    structure without the model knowing about meshes.
  * optional ``remat`` (jax.checkpoint) per block -- the HBM/FLOPs
    trade for long sequences.
"""
from __future__ import annotations

import dataclasses
import functools
from typing import Any, Callable, Dict, Mapping, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

Constrain = Callable[[jax.Array], jax.Array]
# (q [B,S,Hq,D], k [B,S,Hkv,D], v) -> [B,S,Hq,D]; plugs ring/Ulysses
# sequence-parallel attention (parallel/ring_attention.py,
# parallel/sp_ulysses.py) into the block without the model knowing
# about meshes. None -> local full attention.
AttnFn = Optional[Callable[[jax.Array, jax.Array, jax.Array], jax.Array]]


def _identity(x: jax.Array) -> jax.Array:
    return x


# Long-lived processes (the serving engine, multi-config sweeps) keep
# hitting these module-level caches with fresh keys; unbounded, they
# grow for the life of the process. The bounds are sized far above any
# real working set (a server runs ONE config; a sweep runs a handful),
# so steady state never evicts -- and eviction is SAFE anyway: each
# entry is recomputed from its key alone. The one subtlety is
# _make_embed_lookup, whose cache also provides function identity --
# an evicted-and-rebuilt lookup is a new callable, so a jit tracing it
# recompiles (correctness unaffected; tests/test_models.py pins both
# properties).
_CACHE_MAXSIZE = 64


@functools.lru_cache(maxsize=_CACHE_MAXSIZE)
def _make_embed_lookup(vocab: int, table_dtype: str):
    """table[tokens] with a scatter-free backward (see
    LlamaConfig.iota_embed). Factory keyed on the static (vocab,
    dtype) so the custom_vjp residual is just the token array AND so
    repeated traces see the same callable (stable jit cache keys)."""

    @jax.custom_vjp
    def lookup(table: jax.Array, tokens: jax.Array) -> jax.Array:
        return jnp.take(table, tokens, axis=0)

    def fwd(table, tokens):
        return lookup(table, tokens), tokens

    def bwd(tokens, g):
        # dtable[v] = sum over positions with token v of g --
        # expressed as one MXU matmul (one-hot rows are exact
        # selectors) instead of the gather-transpose scatter-add.
        # The (B, S) dims are contracted in place rather than
        # flattened first: under Megatron-SP the cotangent arrives
        # sharded (data, model, None), and a flattening reshape merges
        # two differently-sharded dims -- SPMD can only resolve that by
        # replicating the whole tensor (involuntary full
        # rematerialization). Contracting dims never merge, so each
        # device keeps its (batch, seq) tile and the partial dtables
        # meet in one psum.
        onehot = jax.nn.one_hot(tokens, vocab, dtype=g.dtype)
        batch_dims = tuple(range(g.ndim - 1))
        dtable = jax.lax.dot_general(
            onehot, g,
            ((batch_dims, batch_dims), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return dtable.astype(table_dtype), None

    lookup.defvjp(fwd, bwd)
    return lookup


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    """Parity with ModelArgs (fsdp_tp/llama2_model.py:12-27); defaults
    are the 7B configuration, examples run it tiny."""

    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: Optional[int] = None  # None -> MHA; < n_heads -> GQA
    vocab_size: int = 32000
    multiple_of: int = 256
    ffn_dim_multiplier: Optional[float] = None
    norm_eps: float = 1e-5
    max_seq_len: int = 32768
    depth_init: bool = True
    dtype: Any = jnp.bfloat16       # compute dtype (the reference's
    param_dtype: Any = jnp.float32  # use_amp/amp_dtype pair, utils/config.py:40-44)
    remat: bool = False
    # Matmul-backward embedding lookup: forward is a plain gather
    # (cheap on TPU), but the gradient is computed as one_hot^T @ g on
    # the MXU instead of the gather's transpose scatter-add (TPU
    # scatters serialize; ~5x step slowdown measured). Forward-side
    # one-hot (the naive iota-embed trick) would burn an extra
    # 2*d*vocab FLOPs/token and a [B, S, V] buffer for no benefit.
    iota_embed: bool = True

    @property
    def kv_heads(self) -> int:
        return self.n_kv_heads if self.n_kv_heads is not None else self.n_heads

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    @property
    def ffn_hidden(self) -> int:
        """SwiGLU 2/3 rule + multiple_of rounding (reference :231-272)."""
        hidden = int(2 * (4 * self.dim) / 3)
        if self.ffn_dim_multiplier is not None:
            hidden = int(self.ffn_dim_multiplier * hidden)
        return self.multiple_of * (
            (hidden + self.multiple_of - 1) // self.multiple_of
        )

    def flops_per_token(self, seq_len: Optional[int] = None) -> int:
        """Training FLOPs/token (forward matmul count x 3 for fwd+bwd,
        the 6ND convention) including the causal attention-score/AV
        term at ``seq_len`` (defaults to max_seq_len) -- the
        denominator of MFU accounting."""
        s = seq_len if seq_len is not None else self.max_seq_len
        d, h = self.dim, self.ffn_hidden
        per_layer = (
            2 * d * (self.n_heads + 2 * self.kv_heads) * self.head_dim  # qkv
            + 2 * d * d  # wo
            + 3 * 2 * d * h  # w1,w3,w2
            # QK^T + AV: 2 x 2*S*dim per token, halved by causal mask.
            + 2 * s * d
        )
        embed = 2 * d * self.vocab_size
        return 3 * (self.n_layers * per_layer + embed)


# Llama-2 family shapes (public architecture constants; the reference
# ships only the 7B defaults, fsdp_tp/llama2_model.py:13-16, but its
# planning tables reason about 7B..70B -- docs/guide/
# 11_choosing_a_strategy.md:109-127). 70B is GQA (8 KV heads) with the
# 1.3x/4096-rounded SwiGLU -> ffn_hidden 28672. max_seq_len 4096 = the
# Llama-2 context window; remat on, the configuration large models run.
PRESETS: Dict[str, LlamaConfig] = {
    "7b": LlamaConfig(max_seq_len=4096, remat=True),
    "13b": LlamaConfig(
        dim=5120, n_layers=40, n_heads=40, max_seq_len=4096, remat=True
    ),
    "70b": LlamaConfig(
        dim=8192, n_layers=80, n_heads=64, n_kv_heads=8,
        ffn_dim_multiplier=1.3, multiple_of=4096,
        max_seq_len=4096, remat=True,
    ),
}


@functools.lru_cache(maxsize=_CACHE_MAXSIZE)
def count_params(cfg: "LlamaConfig") -> int:
    """Total trainable parameters for ``cfg``, via eval_shape of the
    real init (no arrays materialized). The single source both
    checks/fit.py (HBM accounting) and checks/roofline.py (memory
    bound) divide by -- two copies would silently disagree the day
    the param tree changes."""
    import numpy as np

    abstract = jax.eval_shape(
        lambda: init_llama(jax.random.key(0), cfg)
    )
    return sum(
        int(np.prod(l.shape)) for l in jax.tree.leaves(abstract)
    )


@functools.lru_cache(maxsize=_CACHE_MAXSIZE)
def count_params_by_part(cfg: "LlamaConfig") -> "Mapping[str, int]":
    """Param counts split by pipeline role: one transformer layer
    (``per_layer``), the token embedding (``embed``), the LM head
    (``head``), and everything else (``other``, the final norm).
    Source for the pipeline-parallel stage-shard accounting in
    checks/fit.py and checks/roofline.py -- derived from the same
    eval_shape tree as count_params, so
    ``per_layer * n_layers + embed + head + other == count_params``.
    Returns an immutable view: the lru_cache hands every caller the
    same object, so a mutable dict would let one caller poison
    pp_worst_stage_params for all later calls."""
    import types

    import numpy as np

    abstract = jax.eval_shape(
        lambda: init_llama(jax.random.key(0), cfg)
    )
    parts = {"per_layer": 0, "embed": 0, "head": 0, "other": 0}
    for key, sub in abstract.items():
        n = sum(int(np.prod(l.shape)) for l in jax.tree.leaves(sub))
        if key == "layers_0":
            parts["per_layer"] = n
        elif key.startswith("layers_"):
            pass  # identical to layers_0 by construction
        elif key == "tok_embeddings":
            parts["embed"] = n
        elif key == "output":
            parts["head"] = n
        else:
            parts["other"] += n
    return types.MappingProxyType(parts)


def pp_worst_stage_params(cfg: "LlamaConfig", stages: int) -> int:
    """Params the fullest pipeline stage holds: its share of the
    layers plus the embed/head edge weights (BOTH on one chip when
    stages == 1; otherwise the bigger of the two, since embed and
    head live on opposite ends of the pipe). The single source for
    the pp byte accounting in checks/fit.py and checks/roofline.py --
    two copies would silently disagree on per-chip bytes."""
    if stages < 1 or cfg.n_layers % stages:
        raise ValueError(
            f"pipeline needs n_layers {cfg.n_layers} divisible by "
            f"the stage count {stages}"
        )
    parts = count_params_by_part(cfg)
    edge = (
        parts["embed"] + parts["head"] if stages == 1
        else max(parts["embed"], parts["head"])
    )
    return (
        parts["per_layer"] * (cfg.n_layers // stages)
        + edge + parts["other"]
    )


def rope_cos_sin(
    seq_len: int,
    head_dim: int,
    theta: float = 10000.0,
    positions: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array]:
    """RoPE tables as fp32 (cos, sin) of shape [seq, head_dim//2].

    Parity: precompute_freqs_cis (reference :30-55); real-pair form
    instead of complex64 -- the rotation is two fused multiply-adds.
    ``positions`` overrides the default 0..seq_len-1 ramp: slot p gets
    the rotation of global position positions[p]. This is what lets a
    permuted token layout (zigzag ring sharding, packed sequences)
    keep exact RoPE without un-permuting activations per layer.
    """
    inv_freq = 1.0 / (
        theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim)
    )
    if positions is None:
        t = jnp.arange(seq_len, dtype=jnp.float32)
    else:
        t = positions.astype(jnp.float32)
    freqs = jnp.outer(t, inv_freq)
    return jnp.cos(freqs), jnp.sin(freqs)


def apply_rope(x: jax.Array, cos: jax.Array, sin: jax.Array) -> jax.Array:
    """Rotate [B, S, H, D] by position. Adjacent-pair convention, fp32
    rotation, result cast back (parity: apply_rotary_emb :58-100).
    ``cos``/``sin`` are [S, D//2] tables shared across the batch, or
    [B, S, D//2] PER-ROW tables (the serving engine's decode step,
    where each batch slot sits at its own position)."""
    orig_dtype = x.dtype
    # Adjacent pairs via a trailing [D//2, 2] reshape -- identical
    # values to the x[..., 0::2]/[..., 1::2] formulation but with
    # contiguous (not lane-strided) access on the minor dim.
    xf = x.astype(jnp.float32).reshape(*x.shape[:-1], x.shape[-1] // 2, 2)
    x1 = xf[..., 0]
    x2 = xf[..., 1]
    # [.., S, D/2] -> [.., S, 1, D/2]: broadcasts over heads either
    # way, and over batch for the shared-table shape.
    c = cos[..., :, None, :]
    s = sin[..., :, None, :]
    r1 = x1 * c - x2 * s
    r2 = x1 * s + x2 * c
    out = jnp.stack([r1, r2], axis=-1).reshape(x.shape)
    return out.astype(orig_dtype)


class RMSNorm(nn.Module):
    """RMSNorm computed in fp32 with a learned scale (parity:
    reference :115-142)."""

    eps: float = 1e-5
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        scale = self.param(
            "scale", nn.initializers.ones, (x.shape[-1],),
            self.param_dtype,
        ).astype(jnp.float32)
        xf = x.astype(jnp.float32)
        normed = xf * jax.lax.rsqrt(
            jnp.mean(xf * xf, axis=-1, keepdims=True) + self.eps
        )
        return (normed * scale).astype(x.dtype)


def _dense(
    features: int, std: float, cfg: "LlamaConfig", name: str
) -> nn.Dense:
    """Bias-free projection with a given init std (the reference's
    nn.init.normal_/trunc_normal_ per-layer std scheme :275-345)."""
    return nn.Dense(
        features,
        use_bias=False,
        dtype=cfg.dtype,
        param_dtype=cfg.param_dtype,
        kernel_init=nn.initializers.normal(stddev=std),
        name=name,
    )


class Attention(nn.Module):
    """Causal self-attention with RoPE and grouped KV heads.

    Parity: reference Attention (:145-228). GQA is expressed as an
    einsum over a [B, S, Hkv, G, D] query view -- no materialised
    repeat_kv copy (:103-112); XLA broadcasts K/V over the group dim.
    """

    cfg: LlamaConfig
    out_std: float
    attn_fn: AttnFn = None

    @nn.compact
    def __call__(
        self, x: jax.Array, positions: Optional[jax.Array] = None
    ) -> jax.Array:
        cfg = self.cfg
        b, s, _ = x.shape
        hd = cfg.head_dim
        n_kv = cfg.kv_heads
        groups = cfg.n_heads // n_kv
        std = 0.02

        q = _dense(cfg.n_heads * hd, std, cfg, "wq")(x)
        k = _dense(n_kv * hd, std, cfg, "wk")(x)
        v = _dense(n_kv * hd, std, cfg, "wv")(x)

        cos, sin = rope_cos_sin(s, hd, positions=positions)
        q = apply_rope(q.reshape(b, s, cfg.n_heads, hd), cos, sin)
        k = apply_rope(k.reshape(b, s, n_kv, hd), cos, sin)
        v = v.reshape(b, s, n_kv, hd)

        if self.attn_fn is not None:
            out = self.attn_fn(q, k, v)
        else:
            # scores [B, Hkv, G, S, S], fp32 softmax, causal mask; GQA
            # via a grouped query view -- no materialised repeat_kv.
            q = q.reshape(b, s, n_kv, groups, hd)
            scale = hd ** -0.5
            scores = jnp.einsum("bqhgd,bkhd->bhgqk", q, k) * scale
            scores = scores.astype(jnp.float32)
            causal = jnp.tril(jnp.ones((s, s), dtype=bool))
            scores = jnp.where(causal, scores, -jnp.inf)
            probs = jax.nn.softmax(scores, axis=-1).astype(cfg.dtype)
            out = jnp.einsum("bhgqk,bkhd->bqhgd", probs, v)
        out = out.reshape(b, s, cfg.n_heads * hd)
        return _dense(cfg.dim, self.out_std, cfg, "wo")(out)


class FeedForward(nn.Module):
    """SwiGLU MLP: w2(silu(w1 x) * w3 x) (parity: reference :231-272)."""

    cfg: LlamaConfig
    out_std: float

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        cfg = self.cfg
        hidden = cfg.ffn_hidden
        gate = _dense(hidden, 0.02, cfg, "w1")(x)
        up = _dense(hidden, 0.02, cfg, "w3")(x)
        return _dense(cfg.dim, self.out_std, cfg, "w2")(
            nn.silu(gate) * up
        )


class TransformerBlock(nn.Module):
    """Pre-norm residual block with depth-scaled output init.

    Parity: reference TransformerBlock (:275-345) -- residual-path
    projections (wo, w2) get std 0.02/sqrt(2*(layer_id+1)) when
    depth_init, else 0.02/sqrt(2*n_layers).
    """

    cfg: LlamaConfig
    layer_id: int
    constrain: Constrain = _identity
    attn_fn: AttnFn = None

    @nn.compact
    def __call__(
        self, x: jax.Array, positions: Optional[jax.Array] = None
    ) -> jax.Array:
        cfg = self.cfg
        depth = (
            self.layer_id + 1 if cfg.depth_init else cfg.n_layers
        )
        out_std = 0.02 / (2 * depth) ** 0.5
        h = x + self.constrain(
            Attention(cfg, out_std, self.attn_fn, name="attention")(
                RMSNorm(cfg.norm_eps, cfg.param_dtype, name="attention_norm")(x),
                positions,
            )
        )
        return h + self.constrain(
            FeedForward(cfg, out_std, name="feed_forward")(
                RMSNorm(cfg.norm_eps, cfg.param_dtype, name="ffn_norm")(h)
            )
        )


class Llama(nn.Module):
    """Parity: reference Transformer (:348-448): token embedding,
    n_layers blocks, final RMSNorm, trunc-normal lm head."""

    cfg: LlamaConfig
    constrain: Constrain = _identity
    attn_fn: AttnFn = None

    @nn.compact
    def __call__(
        self, tokens: jax.Array, positions: Optional[jax.Array] = None
    ) -> jax.Array:
        cfg = self.cfg
        emb = nn.Embed(
            cfg.vocab_size,
            cfg.dim,
            dtype=cfg.dtype,
            param_dtype=cfg.param_dtype,
            embedding_init=nn.initializers.normal(stddev=1.0),
            name="tok_embeddings",
        )
        if cfg.iota_embed:
            # Gather forward, matmul backward (no scatter, no forward
            # one-hot); values identical to emb(tokens) up to the
            # compute-dtype cast.
            lookup = _make_embed_lookup(
                cfg.vocab_size, jnp.dtype(cfg.dtype).name
            )
            x = lookup(emb.embedding.astype(cfg.dtype), tokens)
        else:
            x = emb(tokens)
        x = self.constrain(x)
        block = TransformerBlock
        if cfg.remat:
            block = nn.remat(TransformerBlock)
        for i in range(cfg.n_layers):
            x = block(
                cfg, i, self.constrain, self.attn_fn, name=f"layers_{i}"
            )(x, positions)
        x = RMSNorm(cfg.norm_eps, cfg.param_dtype, name="norm")(x)
        logits = nn.Dense(
            cfg.vocab_size,
            use_bias=False,
            dtype=cfg.dtype,
            param_dtype=cfg.param_dtype,
            kernel_init=nn.initializers.truncated_normal(stddev=0.02),
            name="output",
        )(x)
        # Logits stay in compute dtype: the loss upcasts INSIDE its
        # reductions (losses.cross_entropy), so XLA fuses the fp32
        # cast instead of materialising a [B, S, V] fp32 array in HBM
        # (~1 GiB/step at bench shapes). Value-exact either way -- the
        # matmul output is already rounded to cfg.dtype before any
        # cast.
        return logits


def init_llama(
    rng: jax.Array, cfg: LlamaConfig, constrain: Constrain = _identity
) -> Dict:
    # attn_fn never affects the param tree (the attention op itself is
    # parameter-free), so init always uses the local-attention path --
    # a mesh-bound attn_fn could not run on the tiny init sample anyway.
    model = Llama(cfg, constrain)
    sample = jnp.zeros((1, min(8, cfg.max_seq_len)), jnp.int32)
    return model.init(rng, sample)["params"]


def apply_llama(
    params: Dict,
    tokens: jax.Array,
    cfg: LlamaConfig,
    constrain: Constrain = _identity,
    attn_fn: AttnFn = None,
    positions: Optional[jax.Array] = None,
) -> jax.Array:
    """[B, S] int tokens -> [B, S, vocab] logits in cfg.dtype (the
    loss upcasts to fp32 inside its reductions; see Llama.__call__).
    ``positions`` [S]: global RoPE position of each slot, for permuted
    token layouts (zigzag ring); None = the usual 0..S-1."""
    return Llama(cfg, constrain, attn_fn).apply(
        {"params": params}, tokens, positions
    )


def make_forward(
    cfg: LlamaConfig,
    constrain: Constrain = _identity,
    attn_fn: AttnFn = None,
    positions: Optional[jax.Array] = None,
):
    """Trainer-contract forward: next-token cross-entropy on (inputs,
    targets) token batches (datasets.TokenStream). ``positions`` as in
    :func:`apply_llama` -- pass the dataset's layout positions (e.g.
    ``TokenStream.positions()`` in zigzag mode) so RoPE stays exact
    under a permuted token layout; per-token mean cross-entropy is
    itself permutation-invariant."""
    from tpu_hpc.models.losses import cross_entropy

    def forward(params, model_state, batch, step_rng):
        inputs, targets = batch
        logits = apply_llama(
            params, inputs, cfg, constrain, attn_fn, positions
        )
        return cross_entropy(logits, targets), model_state, {}

    return forward
