"""Llama-2 through the pipeline engine: stage-split the flagship model.

Parity: the reference's pipeline example trains its own dedicated
model (scripts/04_pipeline_parallel_pp/03_pipeline_training.py:198-252,
stage cuts at named attribute boundaries :92-103). Here the flagship
Llama-2 itself runs under ``tpu_hpc.parallel.pp``: its transformer
blocks are homogeneous (the depth-scaled init of llama2.py affects
parameter VALUES, never the applied program), so ``n_layers/S``
consecutive blocks form one shape-preserving stage function and the
whole body pipelines as a single SPMD tick program.

Layout. ``split_params`` regroups ``init_llama``'s param tree into

- ``edges``: tok_embeddings + final norm + output head -- replicated
  over the pipe axis and applied OUTSIDE the pipelined body (a
  rounding error of the FLOPs; keeping the body homogeneous is what
  makes it one program, pp.py module docstring), and
- ``stages``: a [S, ...] stacked tree (stage s = layers
  ``s*lps .. s*lps+lps-1``) to be sharded ``P("pipe")`` so each device
  holds exactly its stage's weights.

``merge_params`` is the exact inverse, so the sequential oracle for
every pipelined run is ``llama2.apply_llama`` itself on the SAME
values -- the correctness anchor tests/test_pp_llama.py pins.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from tpu_hpc.models.llama2 import (
    AttnFn,
    LlamaConfig,
    RMSNorm,
    TransformerBlock,
    _make_embed_lookup,
)
from tpu_hpc.parallel import pp

EDGE_KEYS = ("tok_embeddings", "norm", "output")


def layers_per_stage(cfg: LlamaConfig, n_stages: int) -> int:
    if n_stages < 1 or cfg.n_layers % n_stages:
        raise ValueError(
            f"pipeline needs n_layers {cfg.n_layers} divisible by "
            f"the stage count {n_stages}"
        )
    return cfg.n_layers // n_stages


def split_params(params: Dict, cfg: LlamaConfig, n_stages: int) -> Dict:
    """init_llama tree -> {"edges": {...}, "stages": [S, ...] stacked}.

    Stage s's subtree is {"layer_j": <params of layers_{s*lps+j}>}, so
    the stage function applies its layers in global order.
    """
    lps = layers_per_stage(cfg, n_stages)
    edges = {k: params[k] for k in EDGE_KEYS}
    per_stage = [
        {
            f"layer_{j}": params[f"layers_{s * lps + j}"]
            for j in range(lps)
        }
        for s in range(n_stages)
    ]
    return {"edges": edges, "stages": pp.stack_stage_params(per_stage)}


def merge_params(split: Dict, cfg: LlamaConfig) -> Dict:
    """Exact inverse of :func:`split_params` -- the tree
    ``llama2.apply_llama`` (the sequential oracle) consumes."""
    stages = split["stages"]
    S = jax.tree.leaves(stages)[0].shape[0]
    lps = layers_per_stage(cfg, S)
    out = dict(split["edges"])
    for s in range(S):
        stage = jax.tree.map(lambda a: a[s], stages)
        for j in range(lps):
            out[f"layers_{s * lps + j}"] = stage[f"layer_{j}"]
    return out


def split_params_interleaved(
    params: Dict, cfg: LlamaConfig, n_devices: int, n_chunks: int
) -> Dict:
    """Like :func:`split_params` but in the Megatron virtual-pipeline
    layout for the interleaved schedules: ``n_devices * n_chunks``
    global stages of ``n_layers/(S*v)`` layers each, stacked so device
    s holds chunks {s, S+s, 2S+s, ...} (pp.stack_interleaved_stage_
    params' round-robin placement). Pair with
    ``make_forward(schedule="interleaved"/"interleaved-1f1b",
    n_chunks=v)``."""
    split = split_params(params, cfg, n_devices * n_chunks)
    return {
        "edges": split["edges"],
        "stages": pp.interleave_stacked(split["stages"], n_devices),
    }


def merge_params_interleaved(
    split: Dict, cfg: LlamaConfig, n_devices: int, n_chunks: int
) -> Dict:
    """Exact inverse of :func:`split_params_interleaved` -- undo the
    round-robin placement, then the sequential split."""
    import numpy as np

    S, V = n_devices, n_chunks
    order = [j * S + s for s in range(S) for j in range(V)]
    inv = np.argsort(order)
    stages = jax.tree.map(lambda a: a[inv], split["stages"])
    return merge_params(
        {"edges": split["edges"], "stages": stages}, cfg
    )


def make_stage_fn(
    cfg: LlamaConfig,
    n_stages: int,
    attn_fn: AttnFn = None,
    positions: Optional[jax.Array] = None,
):
    """stage_fn(stage_params, x) for ``pp.pipelined``: applies this
    stage's ``n_layers/S`` TransformerBlocks in order. [B, L, D] ->
    [B, L, D] (shape-preserving, as the tick programs require).

    ``layer_id=0`` is deliberate: the block's layer_id only selects
    the depth-scaled INIT std (llama2.py TransformerBlock docstring);
    the applied computation is identical for every layer, which is
    exactly the homogeneity the single-program pipeline needs. The
    per-layer values arrive through ``stage_params``.
    """
    lps = layers_per_stage(cfg, n_stages)
    block = TransformerBlock(cfg, 0, attn_fn=attn_fn)

    def stage_fn(stage_params, x):
        for j in range(lps):
            x = block.apply(
                {"params": stage_params[f"layer_{j}"]}, x, positions
            )
        return x

    return stage_fn


def embed(edges: Dict, tokens: jax.Array, cfg: LlamaConfig) -> jax.Array:
    """[.., L] int tokens -> [.., L, D] in cfg.dtype -- the same
    gather-forward / matmul-backward lookup as Llama.__call__ (the
    scatter-free embedding gradient, llama2.LlamaConfig.iota_embed)."""
    table = edges["tok_embeddings"]["embedding"]
    if cfg.iota_embed:
        lookup = _make_embed_lookup(
            cfg.vocab_size, jnp.dtype(cfg.dtype).name
        )
        return lookup(table.astype(cfg.dtype), tokens)
    return jnp.take(table.astype(cfg.dtype), tokens, axis=0)


def head(edges: Dict, x: jax.Array, cfg: LlamaConfig) -> jax.Array:
    """Final RMSNorm + LM head -> [.., L, vocab] logits in cfg.dtype
    (the loss upcasts inside its reductions, llama2.Llama.__call__)."""
    x = RMSNorm(cfg.norm_eps, cfg.param_dtype).apply(
        {"params": edges["norm"]}, x
    )
    return x @ edges["output"]["kernel"].astype(cfg.dtype)


def pp_pspecs(split: Dict, axis: str = "pipe") -> Dict:
    """PartitionSpec tree: edges replicated over every mesh axis,
    stages stage-sharded over ``axis`` (pp.stage_pspecs)."""
    return {
        "edges": jax.tree.map(lambda _: P(), split["edges"]),
        "stages": pp.stage_pspecs(split["stages"], axis=axis),
    }


def make_forward(
    cfg: LlamaConfig,
    mesh: Mesh,
    n_microbatches: int,
    axis: str = "pipe",
    schedule: str = "1f1b",
    backward: str = "remat",
    batch_spec: P = P(),
    attn_fn: AttnFn = None,
    positions: Optional[jax.Array] = None,
    remat_stage: bool = False,
    n_chunks: int = 1,
):
    """Trainer-contract forward for pipelined Llama training: embed ->
    pipelined stage body -> head -> next-token cross-entropy, with the
    batch microbatched [B, L] -> [M, B/M, L] around the tick program.
    ``batch_spec`` shards the microbatch rows (e.g. P(None, "data")
    for the PP x DP composition); the pipe axis itself never appears
    in it -- activations are replicated over stages by construction.
    ``remat_stage`` wraps the stage in jax.checkpoint on the autodiff
    schedules -- see pp.pipelined. ``n_chunks`` > 1 selects the
    Megatron virtual-pipeline placement (stack the params with
    :func:`split_params_interleaved`; interleaved schedules only).
    """
    from tpu_hpc.models.losses import cross_entropy

    S = mesh.shape[axis]
    pipe = pp.pipelined(
        make_stage_fn(cfg, S * n_chunks, attn_fn, positions),
        mesh, axis=axis,
        schedule=schedule, batch_spec=batch_spec, backward=backward,
        remat_stage=remat_stage, n_chunks=n_chunks,
    )

    def forward(params, model_state, batch, step_rng):
        inputs, targets = batch
        xs = embed(
            params["edges"], pp.microbatch(inputs, n_microbatches), cfg
        )
        ys = pipe(params["stages"], xs)
        logits = head(params["edges"], ys, cfg)
        loss = cross_entropy(logits, pp.microbatch(targets, n_microbatches))
        return loss, model_state, {}

    return forward


def mpmd_bundle(
    split: Dict,
    cfg: LlamaConfig,
    attn_fn: AttnFn = None,
    positions: Optional[jax.Array] = None,
):
    """Cut the flagship Llama for the MPMD pipeline runtime
    (``tpu_hpc.parallel.mpmd``): ``split_params``' stacked stage tree
    becomes per-stage trees, and the edges stop being replicated --
    tok_embeddings lives in stage 0's fault domain, norm+output (and
    the loss) in stage S-1's. Pair with the same sequential-stack
    layout ``split_params`` produces (the interleaved layouts are an
    SPMD bubble optimization; MPMD dispatch order is the runtime's
    own concern)."""
    from tpu_hpc.models.losses import cross_entropy
    from tpu_hpc.parallel.mpmd import StageBundle

    stages = split["stages"]
    S = jax.tree.leaves(stages)[0].shape[0]
    stage_params = tuple(
        jax.tree.map(lambda a: a[s], stages) for s in range(S)
    )
    edges = split["edges"]

    def embed_fn(ep, tokens):
        return embed(ep, tokens, cfg)

    def loss_fn(hp, y, targets):
        return cross_entropy(head(hp, y, cfg), targets)

    return StageBundle(
        n_stages=S,
        stage_fn=make_stage_fn(cfg, S, attn_fn, positions),
        embed_fn=embed_fn,
        loss_fn=loss_fn,
        stage_params=stage_params,
        embed_params={"tok_embeddings": edges["tok_embeddings"]},
        head_params={"norm": edges["norm"], "output": edges["output"]},
    )
