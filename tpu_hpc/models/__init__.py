from tpu_hpc.models import datasets, losses  # noqa: F401
from tpu_hpc.models.llama2 import Llama, LlamaConfig  # noqa: F401
from tpu_hpc.models.pipeline_transformer import PipeConfig  # noqa: F401
from tpu_hpc.models.unet import SimpleUNet, UNetConfig  # noqa: F401
from tpu_hpc.models.resnet import ResNet, ResNetConfig  # noqa: F401
from tpu_hpc.models.vit import SimpleViT, ViTConfig  # noqa: F401
