"""PipelineTransformer: a causal LM built for stage-wise pipelining.

Parity: the reference's PipelineTransformer
(scripts/04_pipeline_parallel_pp/03_pipeline_training.py:51-120) defines
four *named* stage blocks (stage0..3) so torch's tracer can cut at
attribute boundaries (:92-103,180-184).

TPU-native: stages are not named attributes but an *array axis* -- the
per-stage block params are stacked on a leading dim and sharded over the
``pipe`` mesh axis (see tpu_hpc.parallel.pp). Embedding and LM head run
outside the pipelined body, replicated over the pipe axis (negligible
FLOPs; keeps the pipelined body one homogeneous SPMD program). The
stage block itself is ``layers_per_stage`` pre-LN causal transformer
layers, matching the reference's stage contents (:62-88).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
from flax import linen as nn

# (q, k, v) [B, L, H, Dh] -> out [B, L, H, Dh]; same contract as
# llama2.AttnFn, so the Pallas flash kernel drops in for the einsum
# path (called batch-locally -- inside pp's shard_map each stage owns
# its full microbatch, so no nested shard_map is needed).
AttnFn = Callable[[jax.Array, jax.Array, jax.Array], jax.Array]


@dataclasses.dataclass(frozen=True)
class PipeConfig:
    vocab_size: int = 1000
    dim: int = 256
    n_heads: int = 8
    n_stages: int = 4
    layers_per_stage: int = 2
    max_seq_len: int = 128
    mlp_ratio: int = 4
    dtype: Any = jnp.float32        # compute dtype (reference AMP pair,
    param_dtype: Any = jnp.float32  # resnet_fsdp_training.py:198-204)

    @property
    def n_layers(self) -> int:
        return self.n_stages * self.layers_per_stage

    def flops_per_token(self, seq_len: Optional[int] = None) -> int:
        """Training FLOPs/token (6ND convention, same accounting as
        LlamaConfig.flops_per_token) -- the MFU denominator. Remat
        recompute (the 1f1b schedules' backward) is deliberately NOT
        counted: it is overhead, and counting it would flatter MFU."""
        s = seq_len if seq_len is not None else self.max_seq_len
        d = self.dim
        per_layer = (
            2 * d * 3 * d          # qkv projection
            + 2 * d * d            # out projection
            + 2 * 2 * d * self.mlp_ratio * d  # fc1 + fc2
            + 2 * s * d            # causal QK^T + AV (halved by mask)
        )
        head = 2 * d * self.vocab_size
        return 3 * (self.n_layers * per_layer + head)


class CausalLayer(nn.Module):
    """Pre-LN causal self-attention + GELU MLP (the reference stage
    block's layer, 03_pipeline_training.py:62-88). ``attn_fn``
    replaces the einsum-softmax core when given (e.g. the Pallas
    flash kernel: no [L, L] score buffer)."""

    cfg: PipeConfig
    attn_fn: Optional[AttnFn] = None

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        cfg = self.cfg
        B, L, D = x.shape
        H = cfg.n_heads
        h = nn.LayerNorm(dtype=cfg.dtype, param_dtype=cfg.param_dtype, name="ln1")(x)
        qkv = nn.Dense(3 * D, dtype=cfg.dtype, param_dtype=cfg.param_dtype, name="qkv")(h)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(B, L, H, D // H)
        k = k.reshape(B, L, H, D // H)
        v = v.reshape(B, L, H, D // H)
        if self.attn_fn is not None:
            out = self.attn_fn(q, k, v)
        else:
            scores = jnp.einsum("blhd,bmhd->bhlm", q, k) / jnp.sqrt(D // H)
            mask = jnp.tril(jnp.ones((L, L), bool))
            scores = jnp.where(mask, scores, jnp.finfo(scores.dtype).min)
            attn = jax.nn.softmax(scores.astype(jnp.float32), axis=-1)
            out = jnp.einsum("bhlm,bmhd->blhd", attn.astype(x.dtype), v)
        x = x + nn.Dense(D, dtype=cfg.dtype, param_dtype=cfg.param_dtype, name="proj")(
            out.reshape(B, L, D)
        )
        h = nn.LayerNorm(dtype=cfg.dtype, param_dtype=cfg.param_dtype, name="ln2")(x)
        h = nn.Dense(cfg.mlp_ratio * D, dtype=cfg.dtype, param_dtype=cfg.param_dtype, name="fc1")(h)
        h = nn.gelu(h)
        return x + nn.Dense(D, dtype=cfg.dtype, param_dtype=cfg.param_dtype, name="fc2")(h)


class StageBlock(nn.Module):
    """One pipeline stage: layers_per_stage causal layers.
    Shape-preserving ([B, L, D] -> [B, L, D]) as pp.pipelined requires."""

    cfg: PipeConfig
    attn_fn: Optional[AttnFn] = None

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        for i in range(self.cfg.layers_per_stage):
            x = CausalLayer(self.cfg, self.attn_fn, name=f"layer_{i}")(x)
        return x


def init_pipeline_transformer(rng: jax.Array, cfg: PipeConfig) -> Dict:
    """Returns {embed: {tok, pos}, stages: <stacked [S, ...]>, head:
    {ln_scale, ln_bias, kernel}}. ``stages`` is vmapped-init so every
    stage gets an independent draw, stacked ready for P(pipe) sharding."""
    k_emb, k_pos, k_stage, k_head = jax.random.split(rng, 4)
    dummy = jnp.zeros((1, min(8, cfg.max_seq_len), cfg.dim), cfg.dtype)
    block = StageBlock(cfg)
    stage_keys = jax.random.split(k_stage, cfg.n_stages)
    stages = jax.vmap(lambda k: block.init(k, dummy)["params"])(stage_keys)
    pd = cfg.param_dtype
    return {
        "embed": {
            "tok": (jax.random.normal(
                k_emb, (cfg.vocab_size, cfg.dim), jnp.float32
            ) * 0.02).astype(pd),
            "pos": (jax.random.normal(
                k_pos, (cfg.max_seq_len, cfg.dim), jnp.float32
            ) * 0.02).astype(pd),
        },
        "stages": stages,
        "head": {
            "ln_scale": jnp.ones((cfg.dim,), pd),
            "ln_bias": jnp.zeros((cfg.dim,), pd),
            "kernel": (jax.random.normal(
                k_head, (cfg.dim, cfg.vocab_size), jnp.float32
            ) * 0.02).astype(pd),
        },
    }


def embed(params: Dict, tokens: jax.Array, cfg: PipeConfig) -> jax.Array:
    """[.., L] int tokens -> [.., L, D] activations (token + learned
    positional embedding, reference :64-66)."""
    x = params["embed"]["tok"][tokens] + params["embed"]["pos"][: tokens.shape[-1]]
    return x.astype(cfg.dtype)


def head(params: Dict, x: jax.Array, cfg: PipeConfig) -> jax.Array:
    """Final LayerNorm + LM head -> fp32 logits (reference :89-91)."""
    h = params["head"]
    mu = x.mean(-1, keepdims=True)
    var = ((x - mu) ** 2).mean(-1, keepdims=True)
    x = (x - mu) * jax.lax.rsqrt(var + 1e-5)
    x = x * h["ln_scale"] + h["ln_bias"]
    return (x @ h["kernel"]).astype(jnp.float32)


def make_stage_fn(cfg: PipeConfig, attn_fn: Optional[AttnFn] = None):
    """stage_fn(stage_params, x) for tpu_hpc.parallel.pp.pipelined."""
    block = StageBlock(cfg, attn_fn)

    def stage_fn(stage_params, x):
        return block.apply({"params": stage_params}, x)

    return stage_fn


def apply_sequential(params: Dict, tokens: jax.Array, cfg: PipeConfig) -> jax.Array:
    """Single-device oracle: run all stages in order, no pipelining.
    The correctness reference for the pipeline schedules (the role the
    reference's full-model-on-every-rank construction plays,
    03_pipeline_training.py:166-171)."""
    x = embed(params, tokens, cfg)
    stage_fn = make_stage_fn(cfg)
    for s in range(cfg.n_stages):
        x = stage_fn(jax.tree.map(lambda a: a[s], params["stages"]), x)
    return head(params, x, cfg)


def mpmd_bundle(params: Dict, cfg: PipeConfig,
                attn_fn: Optional[AttnFn] = None):
    """Cut this model for the MPMD pipeline runtime
    (``tpu_hpc.parallel.mpmd``): per-stage param trees off the
    stacked axis, the shape-preserving stage function, and the edge
    functions placed on the edge stages' workers (embed on stage 0,
    head+loss on stage S-1 -- the edge ownership the SPMD engine
    replicates instead). The loss is the per-microbatch mean
    cross-entropy; the runtime's total is the mean over microbatches,
    matching the SPMD engine's per-microbatch loss vector
    bit-for-bit (pinned in tests/test_mpmd.py)."""
    from tpu_hpc.models import losses
    from tpu_hpc.parallel.mpmd import StageBundle

    stage_params = tuple(
        jax.tree.map(lambda a: a[s], params["stages"])
        for s in range(cfg.n_stages)
    )

    def embed_fn(ep, tokens):
        return embed({"embed": ep}, tokens, cfg)

    def loss_fn(hp, y, targets):
        return losses.cross_entropy(head({"head": hp}, y, cfg), targets)

    return StageBundle(
        n_stages=cfg.n_stages,
        stage_fn=make_stage_fn(cfg, attn_fn),
        embed_fn=embed_fn,
        loss_fn=loss_fn,
        stage_params=stage_params,
        embed_params=params["embed"],
        head_params=params["head"],
    )
