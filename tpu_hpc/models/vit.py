"""SimpleViT for weather prediction (regression to pixel space).

Capability parity with the reference's ViT
(scripts/03_tensor_parallel_tp/tensor_parallel_vit.py:82-202):
PatchEmbed conv (:82-90), multi-head attention with *separate* q/k/v
projections chosen deliberately so TP shards heads cleanly (:93-118),
GELU MLP (:126-136), pre-LN blocks (:139-151), learned pos-embed, and
the pixel-space reconstruction head that projects tokens back onto the
lat/lon grid (:154-202).

TPU-first design:
  * NHWC layout end-to-end (TPU conv native; the reference's NCHW is a
    CUDA-ism), so unpatchify is a reshape+transpose to [B, H, W, C].
  * module/param names match parallel/tp.vit_rules: q/k/v_proj + fc1
    Colwise (shard output features), out_proj + fc2 Rowwise -- under
    GSPMD that is one PartitionSpec plan, no module wrapping, and the
    head-count reshape needs no -1 trick (arrays are global; XLA
    shards them under the hood).
  * bf16 compute / fp32 params, fp32 softmax and LayerNorm.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

# (q, k, v) [B, S, H, D] -> [B, S, H, D]: plugs the Pallas flash
# kernel (kernels.attention.blockwise_attention, causal=False) or a
# sequence-parallel attention into the block, same hook design as
# models/llama2.AttnFn.
AttnFn = Optional[Callable[[jax.Array, jax.Array, jax.Array], jax.Array]]


@dataclasses.dataclass(frozen=True)
class ViTConfig:
    """Parity with SimpleViT's constructor surface
    (tensor_parallel_vit.py:154-166)."""

    in_channels: int = 20
    out_channels: int = 20
    patch_size: int = 4
    lat: int = 64
    lon: int = 128
    embed_dim: int = 256
    depth: int = 6
    n_heads: int = 8
    mlp_ratio: int = 4
    dtype: Any = jnp.bfloat16       # compute dtype (reference AMP pair,
    param_dtype: Any = jnp.float32  # resnet_fsdp_training.py:198-204)

    @property
    def h_patches(self) -> int:
        return self.lat // self.patch_size

    @property
    def w_patches(self) -> int:
        return self.lon // self.patch_size

    @property
    def n_patches(self) -> int:
        return self.h_patches * self.w_patches

    @property
    def head_dim(self) -> int:
        return self.embed_dim // self.n_heads


def _dense(
    features: int, dtype, name: str, param_dtype=jnp.float32
) -> nn.Dense:
    return nn.Dense(
        features, dtype=dtype, param_dtype=param_dtype,
        kernel_init=nn.initializers.normal(stddev=0.02), name=name,
    )


class ViTAttention(nn.Module):
    """Separate q/k/v projections -> clean Colwise head sharding
    (the reference's explicit design note, :93-110)."""

    cfg: ViTConfig
    attn_fn: AttnFn = None

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        cfg = self.cfg
        b, n, _ = x.shape
        hd = cfg.head_dim
        q = _dense(cfg.embed_dim, cfg.dtype, "q_proj", cfg.param_dtype)(x)
        k = _dense(cfg.embed_dim, cfg.dtype, "k_proj", cfg.param_dtype)(x)
        v = _dense(cfg.embed_dim, cfg.dtype, "v_proj", cfg.param_dtype)(x)
        q = q.reshape(b, n, cfg.n_heads, hd)
        k = k.reshape(b, n, cfg.n_heads, hd)
        v = v.reshape(b, n, cfg.n_heads, hd)
        if self.attn_fn is not None:
            out = self.attn_fn(q, k, v)
        else:
            s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * hd ** -0.5
            p = jax.nn.softmax(s.astype(jnp.float32), axis=-1)
            out = jnp.einsum("bhqk,bkhd->bqhd", p.astype(cfg.dtype), v)
        return _dense(cfg.embed_dim, cfg.dtype, "out_proj", cfg.param_dtype)(
            out.reshape(b, n, cfg.embed_dim)
        )


class ViTBlock(nn.Module):
    cfg: ViTConfig
    attn_fn: AttnFn = None

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        cfg = self.cfg
        ln = lambda nm: nn.LayerNorm(  # noqa: E731
            dtype=jnp.float32, param_dtype=cfg.param_dtype, name=nm
        )
        x = x + ViTAttention(cfg, self.attn_fn, name="attn")(
            ln("norm1")(x).astype(cfg.dtype)
        )
        h = ln("norm2")(x).astype(cfg.dtype)
        h = _dense(cfg.embed_dim * cfg.mlp_ratio, cfg.dtype, "fc1", cfg.param_dtype)(h)
        h = nn.gelu(h)
        return x + _dense(cfg.embed_dim, cfg.dtype, "fc2", cfg.param_dtype)(h)


class SimpleViT(nn.Module):
    cfg: ViTConfig
    attn_fn: AttnFn = None

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        """[B, lat, lon, Cin] -> [B, lat, lon, Cout]."""
        cfg = self.cfg
        b = x.shape[0]
        p = cfg.patch_size
        # Patch embed: stride-p conv == per-patch linear (:82-90).
        tok = nn.Conv(
            cfg.embed_dim, (p, p), strides=(p, p), padding="VALID",
            dtype=cfg.dtype, param_dtype=cfg.param_dtype, name="patch_embed",
        )(x.astype(cfg.dtype))
        tok = tok.reshape(b, cfg.n_patches, cfg.embed_dim)
        pos = self.param(
            "pos_embed",
            nn.initializers.normal(stddev=0.02),
            (1, cfg.n_patches, cfg.embed_dim),
            cfg.param_dtype,
        )
        tok = tok + pos.astype(cfg.dtype)
        for i in range(cfg.depth):
            tok = ViTBlock(cfg, self.attn_fn, name=f"blocks_{i}")(tok)
        tok = nn.LayerNorm(
            dtype=jnp.float32, param_dtype=cfg.param_dtype, name="norm"
        )(tok)
        # Pixel reconstruction head + unpatchify (:180-202), NHWC.
        px = _dense(cfg.out_channels * p * p, cfg.dtype, "head", cfg.param_dtype)(
            tok.astype(cfg.dtype)
        )
        px = px.reshape(
            b, cfg.h_patches, cfg.w_patches, p, p, cfg.out_channels
        )
        px = px.transpose(0, 1, 3, 2, 4, 5).reshape(
            b, cfg.lat, cfg.lon, cfg.out_channels
        )
        return px.astype(jnp.float32)


def init_vit(rng: jax.Array, cfg: ViTConfig) -> Dict:
    sample = jnp.zeros((1, cfg.lat, cfg.lon, cfg.in_channels))
    return SimpleViT(cfg).init(rng, sample)["params"]


def apply_vit(
    params: Dict, x: jax.Array, cfg: ViTConfig, attn_fn: AttnFn = None
) -> jax.Array:
    return SimpleViT(cfg, attn_fn).apply({"params": params}, x)


def make_forward(cfg: ViTConfig, attn_fn: AttnFn = None):
    """Trainer-contract forward: latitude-weighted MSE regression on
    (input, target) grids (the reference trains its ViT with the same
    loss, tensor_parallel_vit.py:209-217)."""
    from tpu_hpc.models.losses import lat_weighted_mse

    def forward(params, model_state, batch, step_rng):
        x, y = batch
        pred = apply_vit(params, x, cfg, attn_fn)
        return lat_weighted_mse(pred, y), model_state, {}

    return forward
