"""ResNet-18/34/50/101/152 for image classification benchmarks.

Capability parity with the reference's torchvision ResNet usage: the
CIFAR-10 benchmark driver (scripts/main.py:249,268-306: ResNet-18/50/
101/152 selectable, synthetic-data mode) and the FSDP example's
CIFAR-adapted ResNet-18 (resnet_fsdp_training.py:186-191, whose conv1/
maxpool surgery -- 3x3 stem, no maxpool -- is the ``cifar_stem``
flag here).

TPU-first: NHWC, flax BatchNorm with explicit batch_stats state (same
scheme as unet.py), bf16-capable compute dtype, post-activation
residual blocks exactly as torchvision (BasicBlock for 18/34,
Bottleneck with expansion 4 for 50/101/152).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

# torch BatchNorm parity: torch's momentum=0.1 ("fraction of the new
# batch") is flax momentum=0.9 ("fraction of the old average"). Flax's
# default 0.99 converges running stats 10x slower than the torchvision
# models the reference trains -- slow enough that eval-mode accuracy
# stays near chance long after train-mode accuracy saturates (caught
# by the real-data digits run in examples/02).
BN_MOMENTUM = 0.9

STAGE_SIZES = {
    18: (2, 2, 2, 2),
    34: (3, 4, 6, 3),
    50: (3, 4, 6, 3),
    101: (3, 4, 23, 3),
    152: (3, 8, 36, 3),
}


@dataclasses.dataclass(frozen=True)
class ResNetConfig:
    depth: int = 18
    num_classes: int = 10
    # CIFAR stem surgery: 3x3/stride-1 conv1, no maxpool (parity:
    # resnet_fsdp_training.py:188-190). False = ImageNet 7x7/stride-2.
    cifar_stem: bool = True
    dtype: Any = jnp.float32        # compute dtype (reference AMP pair,
    param_dtype: Any = jnp.float32  # resnet_fsdp_training.py:198-204)

    @property
    def stage_sizes(self) -> Sequence[int]:
        return STAGE_SIZES[self.depth]

    @property
    def bottleneck(self) -> bool:
        return self.depth >= 50


def _conv(features, kernel, strides, dtype, name, param_dtype=jnp.float32):
    return nn.Conv(
        features, (kernel, kernel), strides=(strides, strides),
        padding="SAME", use_bias=False, dtype=dtype,
        param_dtype=param_dtype, name=name,
    )


class BasicBlock(nn.Module):
    features: int
    strides: int
    dtype: Any
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool):
        use_avg = not train
        h = _conv(self.features, 3, self.strides, self.dtype, "conv1", self.param_dtype)(x)
        h = nn.BatchNorm(
            momentum=BN_MOMENTUM,
            use_running_average=use_avg, dtype=self.dtype,
            param_dtype=self.param_dtype, name="bn1"
        )(h)
        h = nn.relu(h)
        h = _conv(self.features, 3, 1, self.dtype, "conv2", self.param_dtype)(h)
        h = nn.BatchNorm(
            momentum=BN_MOMENTUM,
            use_running_average=use_avg, dtype=self.dtype,
            param_dtype=self.param_dtype, name="bn2"
        )(h)
        if x.shape != h.shape:
            x = _conv(self.features, 1, self.strides, self.dtype, "down", self.param_dtype)(x)
            x = nn.BatchNorm(
                momentum=BN_MOMENTUM,
                use_running_average=use_avg, dtype=self.dtype,
                param_dtype=self.param_dtype, name="down_bn"
            )(x)
        return nn.relu(x + h)


class Bottleneck(nn.Module):
    features: int
    strides: int
    dtype: Any
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool):
        use_avg = not train
        out_f = self.features * 4
        h = _conv(self.features, 1, 1, self.dtype, "conv1", self.param_dtype)(x)
        h = nn.BatchNorm(
            momentum=BN_MOMENTUM,
            use_running_average=use_avg, dtype=self.dtype,
            param_dtype=self.param_dtype, name="bn1"
        )(h)
        h = nn.relu(h)
        h = _conv(self.features, 3, self.strides, self.dtype, "conv2", self.param_dtype)(h)
        h = nn.BatchNorm(
            momentum=BN_MOMENTUM,
            use_running_average=use_avg, dtype=self.dtype,
            param_dtype=self.param_dtype, name="bn2"
        )(h)
        h = nn.relu(h)
        h = _conv(out_f, 1, 1, self.dtype, "conv3", self.param_dtype)(h)
        h = nn.BatchNorm(
            momentum=BN_MOMENTUM,
            use_running_average=use_avg, dtype=self.dtype,
            param_dtype=self.param_dtype, name="bn3"
        )(h)
        if x.shape != h.shape:
            x = _conv(out_f, 1, self.strides, self.dtype, "down", self.param_dtype)(x)
            x = nn.BatchNorm(
                momentum=BN_MOMENTUM,
                use_running_average=use_avg, dtype=self.dtype,
                param_dtype=self.param_dtype, name="down_bn"
            )(x)
        return nn.relu(x + h)


class ResNet(nn.Module):
    cfg: ResNetConfig

    @nn.compact
    def __call__(self, x, train: bool = True):
        cfg = self.cfg
        use_avg = not train
        x = x.astype(cfg.dtype)
        if cfg.cifar_stem:
            x = _conv(64, 3, 1, cfg.dtype, "conv1", cfg.param_dtype)(x)
        else:
            x = _conv(64, 7, 2, cfg.dtype, "conv1", cfg.param_dtype)(x)
        x = nn.BatchNorm(
            momentum=BN_MOMENTUM,
            use_running_average=use_avg, dtype=cfg.dtype,
            param_dtype=cfg.param_dtype, name="bn1"
        )(x)
        x = nn.relu(x)
        if not cfg.cifar_stem:
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        block = Bottleneck if cfg.bottleneck else BasicBlock
        for stage, n_blocks in enumerate(cfg.stage_sizes):
            features = 64 * (2 ** stage)
            for b in range(n_blocks):
                strides = 2 if (b == 0 and stage > 0) else 1
                x = block(
                    features, strides, cfg.dtype, cfg.param_dtype,
                    name=f"stage{stage + 1}_block{b}",
                )(x, train)
        x = jnp.mean(x, axis=(1, 2))  # global average pool
        x = nn.Dense(
            cfg.num_classes, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
            name="fc",
        )(x)
        return x.astype(jnp.float32)


def init_resnet(
    rng: jax.Array, cfg: ResNetConfig,
    sample_shape: Tuple[int, int, int] = (32, 32, 3),
) -> Tuple[Dict, Dict]:
    """(params, model_state) -- model_state carries BatchNorm running
    stats, same contract as unet.init_unet."""
    variables = ResNet(cfg).init(
        rng, jnp.zeros((1, *sample_shape), jnp.float32), train=False
    )
    params = variables["params"]
    model_state = {k: v for k, v in variables.items() if k != "params"}
    return params, model_state


def apply_resnet(params, model_state, x, cfg: ResNetConfig,
                 train: bool = True):
    """Returns (logits, new_model_state)."""
    model = ResNet(cfg)
    if train:
        out, updates = model.apply(
            {"params": params, **model_state}, x, train=True,
            mutable=["batch_stats"],
        )
        return out, {**model_state, **updates}
    out = model.apply({"params": params, **model_state}, x, train=False)
    return out, model_state


def make_forward(cfg: ResNetConfig):
    """Trainer-contract forward: softmax CE + accuracy on (image,
    label) batches (datasets.CIFARSynthetic)."""
    from tpu_hpc.models.losses import cross_entropy

    def forward(params, model_state, batch, step_rng):
        x, labels = batch
        logits, new_ms = apply_resnet(params, model_state, x, cfg,
                                      train=True)
        acc = jnp.mean(
            (jnp.argmax(logits, -1) == labels).astype(jnp.float32)
        )
        return cross_entropy(logits, labels), new_ms, {"accuracy": acc}

    return forward


def make_eval_forward(cfg: ResNetConfig):
    """Trainer-contract eval forward: inference mode (BatchNorm on
    stored stats), test CE + accuracy -- the reference's Trainer.test
    metric (resnet_fsdp_training.py:138-155)."""
    from tpu_hpc.models.losses import cross_entropy

    def eval_forward(params, model_state, batch):
        x, labels = batch
        logits, _ = apply_resnet(params, model_state, x, cfg, train=False)
        acc = jnp.mean(
            (jnp.argmax(logits, -1) == labels).astype(jnp.float32)
        )
        return cross_entropy(logits, labels), {"accuracy": acc}

    return eval_forward
