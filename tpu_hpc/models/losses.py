"""Loss functions, including the domain-specific latitude-weighted MSE.

Parity: lat-weighted MSE appears four times in the reference
(multinode_ddp_unet.py:221-229 and copies -- SURVEY.md 2.3); softmax
cross-entropy is the LLM/PP loss (03_pipeline_training.py loss_fn).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


def latitude_weights(n_lat: int, dtype=jnp.float32) -> jax.Array:
    """cos(lat) weights normalized to mean 1, for a grid of n_lat rows
    from -90..90 degrees. Parity: multinode_ddp_unet.py:221-226."""
    lats = jnp.linspace(-90.0, 90.0, n_lat, dtype=dtype)
    w = jnp.cos(jnp.deg2rad(lats))
    return w / w.mean()


def lat_weighted_mse(pred: jax.Array, target: jax.Array) -> jax.Array:
    """Latitude-weighted MSE over NHWC grids (lat = dim 1).
    Parity: multinode_ddp_unet.py:221-229 (NCHW there, NHWC here)."""
    w = latitude_weights(pred.shape[1], pred.dtype)
    se = (pred - target) ** 2
    return jnp.mean(se * w[None, :, None, None])


def mse(pred: jax.Array, target: jax.Array) -> jax.Array:
    return jnp.mean((pred - target) ** 2)


def cross_entropy(logits: jax.Array, targets: jax.Array) -> jax.Array:
    """Mean softmax cross-entropy over integer targets ([..., V] vs
    [...]). Computed in float32 regardless of logit dtype (bf16-safe).

    TPU note: the gold logit is selected with an iota-compare mask
    rather than take_along_axis -- a vector compare+reduce instead of a
    gather, whose transpose is elementwise instead of a scatter (TPU
    scatters serialize; this path is ~5x faster end-to-end in the
    training step)."""
    logits = logits.astype(jnp.float32)
    logz = jax.nn.logsumexp(logits, axis=-1)
    vocab = logits.shape[-1]
    mask = targets[..., None] == jnp.arange(vocab, dtype=jnp.int32)
    gold = jnp.sum(jnp.where(mask, logits, 0.0), axis=-1)
    return jnp.mean(logz - gold)
