"""Synthetic datasets: reproducible workloads without data files.

Parity with the reference's fixture strategy (SURVEY.md section 4):
  * ERA5-like weather grids   (multinode_ddp_unet.py:145-164; ViT
    variant tensor_parallel_vit.py:56-75): channels = vars x levels,
    [lat, lon] spatial grid, input->target regression pairs.
  * toy regression pairs      (multinode_ddp_basic.py:89-105,
    distributed_dataloader.py:143-156)
  * random token streams      (03_pipeline_training.py:220-230)

TPU-first design: a dataset here is an *index-stateless generator* --
``batch_at(step) -> pytree`` built from a fold-in of seed and step, not
a stateful iterator. That makes input identical across hosts (each host
slices its own shard), resumable from any step (checkpoint stores only
the step counter), and trivially prefetchable. NHWC layout (TPU conv
native), channels-last -- the reference's NCHW is a CUDA-ism.
"""
from __future__ import annotations

import dataclasses
import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


@functools.lru_cache(maxsize=None)
def _jitted_gen(gen_fn, seed: int, batch_size: int, *static):
    """Cache one jitted generator per (dataset, batch) config so each
    ``batch_at`` call is a single cached dispatch, not a chain of eager
    ops (which costs real wall-clock on remote/async transports)."""
    return jax.jit(functools.partial(gen_fn, seed, batch_size, *static))


@dataclasses.dataclass(frozen=True)
class ERA5Synthetic:
    """ERA5-like synthetic weather grids.

    Parity: ERA5Dataset (multinode_ddp_unet.py:145-164) -- channels =
    n_vars x n_levels, default 181x360 global 1-degree grid (odd lat
    dimension kept deliberately: it exercises the UNet's odd-grid
    upsampling path, reference :203-213).
    """

    n_samples: int = 1024
    n_vars: int = 5
    n_levels: int = 4
    lat: int = 181
    lon: int = 360
    seed: int = 0

    @property
    def channels(self) -> int:
        return self.n_vars * self.n_levels

    @property
    def sample_shape(self) -> Tuple[int, int, int]:
        return (self.lat, self.lon, self.channels)  # NHWC

    @staticmethod
    def _gen(seed, batch_size, lat, lon, channels, step):
        rng = jax.random.fold_in(jax.random.key(seed), step)
        ri, rt = jax.random.split(rng)
        shape = (batch_size, lat, lon, channels)
        x = jax.random.normal(ri, shape, dtype=jnp.float32)
        # target = smooth function of input + noise: learnable signal,
        # same spirit as the reference's random regression pairs.
        y = 0.5 * x + 0.1 * jax.random.normal(rt, shape, dtype=jnp.float32)
        return x, y

    def batch_at(self, step: int, batch_size: int) -> Tuple[jax.Array, jax.Array]:
        """Deterministic (input, target) batch for a global step."""
        return _jitted_gen(
            ERA5Synthetic._gen, self.seed, batch_size,
            self.lat, self.lon, self.channels,
        )(step)

    def traced_batch(self, step, batch_size: int):
        """Traceable generator (step may be a tracer) -- lets the Trainer
        scan whole epochs on-device with zero host->device transfers."""
        return ERA5Synthetic._gen(
            self.seed, batch_size, self.lat, self.lon, self.channels, step
        )


@dataclasses.dataclass(frozen=True)
class ToyRegression:
    """20-feature -> 1-target pairs. Parity: MyTrainDataset
    (multinode_ddp_basic.py:89-105)."""

    n_samples: int = 2048
    in_features: int = 20
    out_features: int = 1
    seed: int = 0

    @staticmethod
    def _gen(seed, batch_size, in_f, out_f, step):
        rng = jax.random.fold_in(jax.random.key(seed), step)
        ri, rt = jax.random.split(rng)
        x = jax.random.normal(ri, (batch_size, in_f))
        y = jax.random.normal(rt, (batch_size, out_f))
        return x, y

    def batch_at(self, step: int, batch_size: int) -> Tuple[jax.Array, jax.Array]:
        return _jitted_gen(
            ToyRegression._gen, self.seed, batch_size,
            self.in_features, self.out_features,
        )(step)

    def traced_batch(self, step, batch_size: int):
        return ToyRegression._gen(
            self.seed, batch_size, self.in_features, self.out_features, step
        )


@dataclasses.dataclass(frozen=True)
class CIFARSynthetic:
    """Synthetic CIFAR-shaped (image, label) batches -- the reference's
    ``--use_syn`` mode for the ResNet benchmark (scripts/main.py:
    268-271), which exists so throughput runs need no data download."""

    n_classes: int = 10
    size: int = 32
    channels: int = 3
    seed: int = 0

    @property
    def sample_shape(self) -> Tuple[int, int, int]:
        return (self.size, self.size, self.channels)

    @staticmethod
    def _gen(seed, batch_size, size, channels, n_classes, step):
        rng = jax.random.fold_in(jax.random.key(seed), step)
        ri, rl = jax.random.split(rng)
        x = jax.random.normal(
            ri, (batch_size, size, size, channels), dtype=jnp.float32
        )
        labels = jax.random.randint(
            rl, (batch_size,), 0, n_classes, dtype=jnp.int32
        )
        return x, labels

    def batch_at(self, step: int, batch_size: int):
        return _jitted_gen(
            CIFARSynthetic._gen, self.seed, batch_size,
            self.size, self.channels, self.n_classes,
        )(step)

    def traced_batch(self, step, batch_size: int):
        return CIFARSynthetic._gen(
            self.seed, batch_size, self.size, self.channels,
            self.n_classes, step,
        )


@dataclasses.dataclass(frozen=True)
class TokenStream:
    """Random token batches for LLM/PP training. Parity:
    03_pipeline_training.py:220-230 (inputs + shifted targets).

    ``zigzag_ring=n`` emits every batch in the zigzag layout for an
    n-way ring (slot p holds the token of global position
    ``zigzag_indices(n, seq_len)[0][p]``; inputs and targets permute
    together, so the next-token pairing is preserved). This is the
    pay-once-at-the-loader layout
    ``parallel.ring_attention.make_zigzag_ring_attn_fn(...,
    data_layout="zigzag")`` consumes -- feed ``positions()`` to the
    model so RoPE uses global coordinates.
    """

    vocab_size: int = 32000
    seq_len: int = 2048
    seed: int = 0
    zigzag_ring: Optional[int] = None

    def _perm(self):
        """Zigzag layout permutation (None in contiguous mode)."""
        if self.zigzag_ring is None:
            return None
        from tpu_hpc.parallel.ring_attention import zigzag_indices

        return zigzag_indices(self.zigzag_ring, self.seq_len)[0]

    def positions(self) -> Optional[jax.Array]:
        """Global RoPE position of each slot ([seq_len] int32), for
        ``llama2.make_forward(..., positions=...)``. None in
        contiguous mode (the model's default ramp is already right).
        """
        perm = self._perm()
        return None if perm is None else perm.astype(jnp.int32)

    @staticmethod
    def _gen(seed, batch_size, seq_len, vocab, ring, step):
        rng = jax.random.fold_in(jax.random.key(seed), step)
        tokens = jax.random.randint(
            rng, (batch_size, seq_len + 1), 0, vocab, dtype=jnp.int32
        )
        inputs, targets = tokens[:, :-1], tokens[:, 1:]
        if ring is not None:
            from tpu_hpc.parallel.ring_attention import zigzag_indices

            idx = zigzag_indices(ring, seq_len)[0]
            inputs, targets = inputs[:, idx], targets[:, idx]
        return inputs, targets

    def batch_at(self, step: int, batch_size: int) -> Tuple[jax.Array, jax.Array]:
        return _jitted_gen(
            TokenStream._gen, self.seed, batch_size,
            self.seq_len, self.vocab_size, self.zigzag_ring,
        )(step)

    def traced_batch(self, step, batch_size: int):
        return TokenStream._gen(
            self.seed, batch_size, self.seq_len, self.vocab_size,
            self.zigzag_ring, step,
        )


def shard_batch(batch, mesh, axis: str = "data"):
    """Place a host-global batch onto the mesh, batch dim sharded over
    ``axis`` -- the DistributedSampler equivalent: each data shard sees
    a distinct slice (multinode_ddp_unet.py:283-292)."""
    from jax.sharding import NamedSharding, PartitionSpec

    spec = NamedSharding(mesh, PartitionSpec(axis))
    return jax.tree.map(lambda a: jax.device_put(a, spec), batch)
