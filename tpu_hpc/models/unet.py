"""SimpleUNet: 3-level conv U-Net for ERA5-like weather grids.

Capability parity with the reference's SimpleUNet
(multinode_ddp_unet.py:171-214, copy in multinode_fsdp_unet.py:69-112):
3-level encoder/decoder with BatchNorm and bilinear-interpolation
upsampling so odd grid sizes (181 lat) survive the down/up round trip
(reference :203-213).

TPU-first deltas: NHWC layout (XLA:TPU's native conv layout -- NCHW is
a CUDA-ism), flax.linen module with explicit batch_stats state instead
of in-place running stats, and a channels-last 1x1 projection head.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from tpu_hpc.models.resnet import BN_MOMENTUM


@dataclasses.dataclass(frozen=True)
class UNetConfig:
    in_channels: int = 20
    out_channels: int = 20
    base_features: int = 64
    dtype: Any = jnp.float32        # compute dtype (reference AMP pair,
    param_dtype: Any = jnp.float32  # resnet_fsdp_training.py:198-204)


class ConvBlock(nn.Module):
    """(Conv3x3 -> BN -> ReLU) x 2."""

    features: int
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool):
        for _ in range(2):
            x = nn.Conv(self.features, (3, 3), padding="SAME",
                        dtype=self.dtype,
                        param_dtype=self.param_dtype)(x)
            x = nn.BatchNorm(use_running_average=not train,
                             momentum=BN_MOMENTUM,
                             dtype=self.dtype,
                             param_dtype=self.param_dtype)(x)
            x = nn.relu(x)
        return x


def _bilinear_resize(x: jax.Array, hw: Tuple[int, int]) -> jax.Array:
    """Bilinear upsample to an exact target size -- handles odd grids,
    parity with the reference's F.interpolate trick (:203-213)."""
    b, _, _, c = x.shape
    return jax.image.resize(x, (b, hw[0], hw[1], c), method="bilinear")


class SimpleUNet(nn.Module):
    config: UNetConfig

    @nn.compact
    def __call__(self, x, train: bool = True):
        cfg = self.config
        f = cfg.base_features
        x = x.astype(cfg.dtype)

        e1 = ConvBlock(f, cfg.dtype, cfg.param_dtype, name="enc1")(x, train)
        p1 = nn.max_pool(e1, (2, 2), strides=(2, 2))
        e2 = ConvBlock(2 * f, cfg.dtype, cfg.param_dtype, name="enc2")(p1, train)
        p2 = nn.max_pool(e2, (2, 2), strides=(2, 2))

        b = ConvBlock(4 * f, cfg.dtype, cfg.param_dtype, name="bottleneck")(p2, train)

        u2 = _bilinear_resize(b, e2.shape[1:3])
        d2 = ConvBlock(2 * f, cfg.dtype, cfg.param_dtype, name="dec2")(
            jnp.concatenate([u2, e2], axis=-1), train
        )
        u1 = _bilinear_resize(d2, e1.shape[1:3])
        d1 = ConvBlock(f, cfg.dtype, cfg.param_dtype, name="dec1")(
            jnp.concatenate([u1, e1], axis=-1), train
        )
        out = nn.Conv(cfg.out_channels, (1, 1), dtype=cfg.dtype,
                      param_dtype=cfg.param_dtype,
                      name="head")(d1)
        return out.astype(jnp.float32)


def init_unet(
    rng: jax.Array, cfg: UNetConfig, sample_shape: Tuple[int, int, int]
) -> Tuple[Dict, Dict]:
    """Initialize (params, model_state). model_state carries BatchNorm
    running stats (the reference mutates them in-place; here they are
    explicit trainer-managed state)."""
    model = SimpleUNet(cfg)
    variables = model.init(
        rng, jnp.zeros((1, *sample_shape), jnp.float32), train=False
    )
    params = variables["params"]
    model_state = {k: v for k, v in variables.items() if k != "params"}
    return params, model_state


def apply_unet(params, model_state, x, cfg: UNetConfig, train: bool = True):
    """Returns (prediction, new_model_state)."""
    model = SimpleUNet(cfg)
    if train:
        out, updates = model.apply(
            {"params": params, **model_state}, x, train=True,
            mutable=["batch_stats"],
        )
        return out, {**model_state, **updates}
    out = model.apply({"params": params, **model_state}, x, train=False)
    return out, model_state


def make_eval_forward(cfg: UNetConfig):
    """Trainer-contract eval forward: inference mode (BatchNorm on
    stored stats), latitude-weighted test MSE -- the reference's UNet
    test pass (multinode_fsdp_unet.py test loss)."""
    from tpu_hpc.models.losses import lat_weighted_mse

    def eval_forward(params, model_state, batch):
        x, y = batch
        pred, _ = apply_unet(params, model_state, x, cfg, train=False)
        return lat_weighted_mse(pred, y), {}

    return eval_forward
