"""tpu_hpc -- a TPU-native distributed training framework.

Capability match for the reference recipe collection
``negin513/distributed-pytorch-hpc`` (multi-node PyTorch/NCCL on NCAR
Derecho), re-designed from scratch for TPU: one ``jax.sharding.Mesh`` +
PartitionSpec mechanism replaces the DDP/FSDP/DTensor/pipelining wrapper
zoo; XLA collectives over ICI/DCN replace NCCL over NVLink/Slingshot;
``jax.distributed.initialize`` replaces the mpiexec/torchrun launcher
detection matrix.

Layer map (mirrors SURVEY.md section 1):
  runtime/    distributed init, mesh construction, topology introspection
  comm/       collective primitives + ICI/DCN benchmark suite
  parallel/   named parallelism recipes: dp, fsdp, tp, pp, sp, ring, domain
  models/     llama2, unet, vit, pipeline transformer, synthetic datasets
  train/      trainer loop, throughput metrics, losses
  ckpt/       orbax checkpointing + snapshot auto-resume
  resilience/ preemption guard, hang watchdog, retry/backoff, run
              supervisor, deterministic fault injection
  config/     unified dataclass + YAML/CLI config
  profiling/  jax.profiler wrapper with schedule windows
  logging_/   host-0 logging, per-process output redirect
  checks/     environment verification
  kernels/    pallas kernels (flash / ring attention)
"""

__version__ = "0.1.0"

import os as _os

# CPU-simulated mesh escape hatch: TPU_HPC_SIM_DEVICES=N forces the
# host CPU platform with N virtual devices, regardless of any
# pre-registered accelerator plugin (hosting sitecustomize may clobber
# JAX_PLATFORMS/XLA_FLAGS passed on the command line). This is the
# no-cluster development mode the reference lacks entirely (SURVEY.md
# section 4: "multi-node without a cluster: not solved").
_sim = _os.environ.get("TPU_HPC_SIM_DEVICES")
if _sim:
    from tpu_hpc.runtime.sim import force_sim_devices as _force_sim

    _force_sim(int(_sim))


def _install_jax_compat() -> None:
    """Runtime-version shims: the framework targets the current stable
    jax API; on older runtimes (e.g. the 0.4.x this container ships)
    a few entry points are missing or spelled differently. Install
    equivalent adapters at the same names so every module and recipe
    runs unchanged on both. Each shim self-disables the day the
    baseline jax has the real thing.

    * ``jax.shard_map`` -- lives under ``jax.experimental.shard_map``
      with ``check_rep`` instead of ``check_vma``.
    * ``jax.lax.axis_size`` -- ``psum(1, axis)`` constant-folds to the
      static axis size under shard_map tracing, which is exactly what
      the newer helper returns.
    """
    import jax as _jax

    if not hasattr(_jax, "shard_map"):
        from jax.experimental.shard_map import shard_map as _shard_map

        def shard_map(f, *, mesh=None, in_specs, out_specs,
                      check_vma=None, **kwargs):
            if check_vma is not None:
                kwargs["check_rep"] = check_vma
            return _shard_map(
                f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                **kwargs,
            )

        _jax.shard_map = shard_map

    if not hasattr(_jax.lax, "axis_size"):
        def axis_size(axis_name):
            return _jax.lax.psum(1, axis_name)

        _jax.lax.axis_size = axis_size

    # jax.P / jax.NamedSharding graduated to top-level aliases after
    # 0.4.x; env_check's all_reduce_smoke (and current-API user code)
    # spells them the new way.
    if not hasattr(_jax, "P"):
        _jax.P = _jax.sharding.PartitionSpec
    if not hasattr(_jax, "NamedSharding"):
        _jax.NamedSharding = _jax.sharding.NamedSharding

    # The *_with_path family graduated from jax.tree_util to jax.tree
    # after 0.4.x; alias the originals.
    for _name in (
        "flatten_with_path", "leaves_with_path", "map_with_path"
    ):
        if not hasattr(_jax.tree, _name):
            setattr(
                _jax.tree, _name,
                getattr(_jax.tree_util, f"tree_{_name}"),
            )


_install_jax_compat()

from tpu_hpc.runtime import (  # noqa: F401
    HostInfo,
    MeshSpec,
    build_mesh,
    cleanup_distributed,
    get_host_info,
    init_distributed,
    is_main_host,
    print_host0,
)
