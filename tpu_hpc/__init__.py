"""tpu_hpc -- a TPU-native distributed training framework.

Capability match for the reference recipe collection
``negin513/distributed-pytorch-hpc`` (multi-node PyTorch/NCCL on NCAR
Derecho), re-designed from scratch for TPU: one ``jax.sharding.Mesh`` +
PartitionSpec mechanism replaces the DDP/FSDP/DTensor/pipelining wrapper
zoo; XLA collectives over ICI/DCN replace NCCL over NVLink/Slingshot;
``jax.distributed.initialize`` replaces the mpiexec/torchrun launcher
detection matrix.

Layer map (mirrors SURVEY.md section 1):
  runtime/    distributed init, mesh construction, topology introspection
  comm/       collective primitives + ICI/DCN benchmark suite
  parallel/   named parallelism recipes: dp, fsdp, tp, pp, sp, ring, domain
  models/     llama2, unet, vit, pipeline transformer, synthetic datasets
  train/      trainer loop, throughput metrics, losses
  ckpt/       orbax checkpointing + snapshot auto-resume
  config/     unified dataclass + YAML/CLI config
  profiling/  jax.profiler wrapper with schedule windows
  logging_/   host-0 logging, per-process output redirect
  checks/     environment verification
  kernels/    pallas kernels (flash / ring attention)
"""

__version__ = "0.1.0"

import os as _os

# CPU-simulated mesh escape hatch: TPU_HPC_SIM_DEVICES=N forces the
# host CPU platform with N virtual devices, regardless of any
# pre-registered accelerator plugin (hosting sitecustomize may clobber
# JAX_PLATFORMS/XLA_FLAGS passed on the command line). This is the
# no-cluster development mode the reference lacks entirely (SURVEY.md
# section 4: "multi-node without a cluster: not solved").
_sim = _os.environ.get("TPU_HPC_SIM_DEVICES")
if _sim:
    from tpu_hpc.runtime.sim import force_sim_devices as _force_sim

    _force_sim(int(_sim))

from tpu_hpc.runtime import (  # noqa: F401
    HostInfo,
    MeshSpec,
    build_mesh,
    cleanup_distributed,
    get_host_info,
    init_distributed,
    is_main_host,
    print_host0,
)
