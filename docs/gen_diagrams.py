"""Generate the guide's teaching diagrams as deterministic SVGs.

The reference guide teaches with ~19 images (pipeline timelines,
TP column/row figures, halo arrays -- /root/reference/docs/images/);
this script is the TPU edition: every figure is generated from the
*actual* schedule formulas and layouts the code runs (pp.py tick
programs, ring_attention zigzag indices, fsdp mode pspecs), so the
diagrams cannot drift from the implementation the way hand-drawn
images do. Run ``python docs/gen_diagrams.py`` to (re)build
``docs/guide/images/*.svg``; CI builds the site with --strict so a
missing image fails the build.
"""
from __future__ import annotations

import pathlib

import matplotlib

matplotlib.use("Agg")
import matplotlib.pyplot as plt
from matplotlib.patches import FancyArrow, Rectangle

OUT = pathlib.Path(__file__).parent / "guide" / "images"

# Okabe-Ito colorblind-safe palette; microbatches cycle through it.
MB_COLORS = [
    "#0072B2", "#E69F00", "#009E73", "#CC79A7",
    "#56B4E9", "#D55E00", "#F0E442", "#999999",
]
FWD_ALPHA, BWD_ALPHA = 1.0, 0.45
EDGE = "#333333"

plt.rcParams.update({
    "font.family": "DejaVu Sans",
    "font.size": 9,
    "svg.hashsalt": "tpu_hpc",   # deterministic ids
})


def _save(fig, name):
    OUT.mkdir(parents=True, exist_ok=True)
    fig.savefig(OUT / name, format="svg", bbox_inches="tight",
                metadata={"Date": None})
    plt.close(fig)
    print(f"wrote {OUT / name}")


def _cell(ax, t, row, mb, kind, label, h=0.8):
    color = MB_COLORS[mb % len(MB_COLORS)]
    alpha = FWD_ALPHA if kind == "F" else BWD_ALPHA
    ax.add_patch(Rectangle(
        (t, row - h / 2), 1, h, facecolor=color, alpha=alpha,
        edgecolor=EDGE, linewidth=0.5,
    ))
    ax.text(t + 0.5, row, label, ha="center", va="center",
            fontsize=6.5, color="white" if kind == "F" else "#222")


def _schedule_axes(ax, n_rows, n_ticks, row_labels, title):
    ax.set_xlim(0, n_ticks)
    ax.set_ylim(n_rows - 0.5, -0.5)
    ax.set_yticks(range(n_rows))
    ax.set_yticklabels(row_labels)
    ax.set_xlabel("tick")
    ax.set_title(title, fontsize=10, loc="left")
    ax.tick_params(length=0)
    for spine in ax.spines.values():
        spine.set_visible(False)


def pipeline_schedules(S=4, M=8, V=2):
    """GPipe vs 1F1B vs interleaved-1F1B, from the pp.py tick formulas.

    Each device row is split: top half = forward ops, bottom half =
    backward ops (the scan body runs one of each per tick)."""
    fig, axes = plt.subplots(3, 1, figsize=(11, 7.6),
                             gridspec_kw={"hspace": 0.55})

    # -- GPipe: F_f at t=f+s; all backwards after the drain, reverse
    # order (autodiff transposes the forward ticks).
    ax = axes[0]
    Tf = M + S - 1
    for s in range(S):
        for f in range(M):
            _cell(ax, f + s, s, f, "F", f"F{f}", h=0.38)
        for b in range(M - 1, -1, -1):
            t = Tf + (M - 1 - b) + (S - 1 - s)
            _cell(ax, t, s + 0.41, b, "B", f"B{b}", h=0.38)
    _schedule_axes(
        ax, S, 2 * (M + S - 1),
        [f"dev {s}" for s in range(S)],
        f"GPipe  (S={S}, M={M}): all forwards, then all backwards -- "
        f"O(M) live activations, bubble 2(S-1) ticks",
    )

    # -- 1F1B: F_f at t=f+s, B_b at t=2S-1-s+b (pp.py:291-296).
    ax = axes[1]
    for s in range(S):
        for f in range(M):
            _cell(ax, f + s, s - 0.205, f, "F", f"F{f}", h=0.38)
        for b in range(M):
            _cell(ax, 2 * S - 1 - s + b, s + 0.205, b, "B", f"B{b}",
                  h=0.38)
    _schedule_axes(
        ax, S, M + 2 * S - 1,
        [f"dev {s}" for s in range(S)],
        f"1F1B  (S={S}, M={M}): B_b follows S-s ticks behind F -- "
        f"O(S) live inputs, same bubble as GPipe, steady-state 1F+1B "
        f"per tick",
    )

    # -- Interleaved 1F1B: F of (g=jS+s, f=qS+r) at t=qVS+g+r;
    # B at VS+qVS+(V-1-j)S+(S-1-s)+r (pp.py interleaved-1f1b).
    ax = axes[2]
    G = S * V
    for s in range(S):
        for j in range(V):
            g = j * S + s
            for f in range(M):
                q, r = f // S, f % S
                t = q * V * S + g + r
                _cell(ax, t, s - 0.205, f, "F", f"F{f}", h=0.38)
                u = V * S + q * V * S + (V - 1 - j) * S + (S - 1 - s) + r
                _cell(ax, u, s + 0.205, f, "B", f"B{f}", h=0.38)
    for s in range(S):
        ax.text(-1.6, s - 0.205, "c0|c1", fontsize=5.5, ha="right",
                va="center", color="#666")
    _schedule_axes(
        ax, S, M * V + V * S + S - 1,
        [f"dev {s}" for s in range(S)],
        f"Interleaved 1F1B  (S={S}, v={V}, M={M}): each device runs "
        f"v={V} model chunks round-robin -- ramp/drain shrinks to "
        f"(S-1)/v, live inputs O(S*v), not O(M)",
    )
    _save(fig, "pipeline_schedules.svg")


def mesh_torus(nx=4, ny=4):
    """2D device mesh with ICI torus links and sharding-axis arrows."""
    fig, ax = plt.subplots(figsize=(6.4, 5.6))
    for x in range(nx):
        for y in range(ny):
            ax.add_patch(Rectangle(
                (x - 0.28, y - 0.28), 0.56, 0.56, facecolor="#0072B2",
                alpha=0.85, edgecolor=EDGE, zorder=3,
            ))
            ax.text(x, y, f"{x},{y}", ha="center", va="center",
                    color="white", fontsize=8, zorder=4)
    for x in range(nx):
        for y in range(ny):
            if x + 1 < nx:
                ax.plot([x + 0.28, x + 0.72], [y, y], color="#999",
                        lw=1.6, zorder=1)
            if y + 1 < ny:
                ax.plot([x, x], [y + 0.28, y + 0.72], color="#999",
                        lw=1.6, zorder=1)
    # Torus wraparound links (dashed arcs).
    for y in range(ny):
        ax.plot([-0.28, -0.75], [y, y], color="#bbb", lw=1.2, ls="--")
        ax.plot([nx - 1 + 0.28, nx - 1 + 0.75], [y, y], color="#bbb",
                lw=1.2, ls="--")
    for x in range(nx):
        ax.plot([x, x], [-0.28, -0.75], color="#bbb", lw=1.2, ls="--")
        ax.plot([x, x], [ny - 1 + 0.28, ny - 1 + 0.75], color="#bbb",
                lw=1.2, ls="--")
    ax.add_patch(FancyArrow(-1.2, -0.1, 0, ny - 0.9, width=0.02,
                            head_width=0.12, color="#D55E00"))
    ax.text(-1.45, (ny - 1) / 2, 'mesh axis "data" (FSDP/DP shards)',
            rotation=90, va="center", fontsize=9, color="#D55E00")
    ax.add_patch(FancyArrow(-0.1, -1.2, nx - 0.9, 0, width=0.02,
                            head_width=0.12, color="#009E73"))
    ax.text((nx - 1) / 2, -1.45, 'mesh axis "model" (TP shards)',
            ha="center", fontsize=9, color="#009E73")
    ax.text((nx - 1) / 2, ny - 0.1 + 0.9,
            "dashed = ICI wraparound (torus): every axis is a ring",
            ha="center", fontsize=8.5, color="#777")
    ax.set_xlim(-1.9, nx + 0.6)
    ax.set_ylim(-1.9, ny + 0.8)
    ax.set_aspect("equal")
    ax.axis("off")
    ax.set_title(
        f'Mesh(devices.reshape({ny},{nx}), ("data","model")) on the '
        "ICI torus", fontsize=10, loc="left",
    )
    _save(fig, "mesh_torus.svg")


def zigzag_ring(S=4, C=8):
    """Contiguous vs zigzag sequence sharding for ring attention:
    per-device causal-work bars from the actual chunk indices."""
    fig, axes = plt.subplots(2, 1, figsize=(8.6, 4.6),
                             gridspec_kw={"hspace": 0.9})
    n = C  # chunks (2 per device for zigzag)
    assign_contig = {d: [2 * d, 2 * d + 1] for d in range(S)}
    assign_zig = {d: [d, 2 * S - 1 - d] for d in range(S)}
    for ax, assign, name in (
        (axes[0], assign_contig, "contiguous"),
        (axes[1], assign_zig, "zigzag"),
    ):
        for d, chunks in assign.items():
            for c in chunks:
                ax.add_patch(Rectangle(
                    (c, 0), 1, 0.8, facecolor=MB_COLORS[d],
                    edgecolor=EDGE, lw=0.6,
                ))
                ax.text(c + 0.5, 0.4, f"d{d}", ha="center",
                        va="center", color="white", fontsize=8)
        # causal work per device = sum over owned chunks c of (c+1)
        # kv-chunks attended (lower-triangular block count).
        work = {d: sum(c + 1 for c in cs) for d, cs in assign.items()}
        wmax = max(work.values())
        for d in range(S):
            ax.add_patch(Rectangle(
                (n + 0.7 + d * 1.1, 0), 0.9, 0.8 * work[d] / wmax,
                facecolor=MB_COLORS[d], edgecolor=EDGE, lw=0.6,
            ))
            ax.text(n + 0.7 + d * 1.1 + 0.45, -0.26, f"d{d}",
                    ha="center", fontsize=7)
        spread = max(work.values()) / min(work.values())
        ax.text(n + 0.7 + S * 1.1 + 0.3, 0.4,
                f"max/min\n= {spread:.2f}x", fontsize=8, va="center")
        ax.set_xlim(-0.2, n + S * 1.1 + 2.6)
        ax.set_ylim(-0.5, 1.05)
        ax.axis("off")
        ax.set_title(
            f"{name}: sequence chunks 0..{n - 1} -> devices  |  "
            "causal work per device", fontsize=9.5, loc="left",
        )
    fig.suptitle(
        "Zigzag ring attention: pairing chunk d with chunk 2S-1-d "
        "equalises causal work (ring_attention.py zigzag_indices)",
        fontsize=10, x=0.01, ha="left",
    )
    _save(fig, "zigzag_ring.svg")


def halo_exchange(S=4, W=6):
    """1D domain decomposition with ghost cells and the two ppermute
    hops that fill them (domain.py halo_exchange)."""
    fig, ax = plt.subplots(figsize=(9.2, 2.9))
    gap = 1.1
    for d in range(S):
        x0 = d * (W + gap)
        for i in range(W):
            ax.add_patch(Rectangle(
                (x0 + i, 0), 1, 1, facecolor=MB_COLORS[d], alpha=0.85,
                edgecolor=EDGE, lw=0.6,
            ))
        # ghost cells
        for gx, src in ((x0 - 0.95, d - 1), (x0 + W - 0.05, d + 1)):
            if 0 <= src < S:
                ax.add_patch(Rectangle(
                    (gx, 0), 0.92, 1, facecolor=MB_COLORS[src],
                    alpha=0.3, edgecolor=EDGE, lw=0.6, ls="--",
                ))
        ax.text(x0 + W / 2, -0.42, f"device {d}", ha="center",
                fontsize=9)
    for d in range(S - 1):
        x_r = d * (W + gap) + W - 1 + 0.5       # my last interior cell
        x_gr = (d + 1) * (W + gap) - 0.5        # right nbr's left ghost
        ax.annotate(
            "", xy=(x_gr, 1.35), xytext=(x_r, 1.15),
            arrowprops=dict(arrowstyle="->", color="#D55E00", lw=1.4,
                            connectionstyle="arc3,rad=-0.3"),
        )
        x_l = (d + 1) * (W + gap) + 0.5
        x_gl = d * (W + gap) + W + 0.4
        ax.annotate(
            "", xy=(x_gl, -0.75), xytext=(x_l, -0.62),
            arrowprops=dict(arrowstyle="->", color="#0072B2", lw=1.4,
                            connectionstyle="arc3,rad=-0.3"),
        )
    ax.text(0, 1.9, "ppermute(+1): send right edge -> right "
            "neighbor's left ghost", color="#D55E00", fontsize=9)
    ax.text(0, -1.35, "ppermute(-1): send left edge -> left "
            "neighbor's right ghost", color="#0072B2", fontsize=9)
    ax.set_xlim(-1.4, S * (W + gap))
    ax.set_ylim(-1.7, 2.3)
    ax.axis("off")
    ax.set_title(
        "Halo exchange: solid = owned cells, dashed = ghost cells "
        "(width = stencil radius)", fontsize=10, loc="left",
    )
    _save(fig, "halo_exchange.svg")


def fsdp_modes():
    """The four FSDP sharding modes as a state matrix
    (fsdp.py param_pspecs / grad_op_pspecs / hybrid_shard_pspecs)."""
    modes = [
        ("FULL_SHARD", ["sharded", "sharded", "sharded"],
         "gather params per layer fwd+bwd; reduce-scatter grads"),
        ("SHARD_GRAD_OP", ["replicated", "sharded", "sharded"],
         "params stay whole; only grads + optimizer state shard"),
        ("NO_SHARD (= DP)", ["replicated", "replicated", "replicated"],
         "plain data parallel; all-reduce grads"),
        ("HYBRID_SHARD", ["sharded in node", "sharded in node",
                          "sharded in node"],
         "FULL_SHARD inside an ICI slice, DP all-reduce across DCN"),
    ]
    cols = ["params", "grads", "opt state"]
    color = {
        "sharded": "#009E73", "replicated": "#D55E00",
        "sharded in node": "#56B4E9",
    }
    fig, ax = plt.subplots(figsize=(8.6, 3.4))
    for r, (name, cells, note) in enumerate(modes):
        ax.text(-0.15, r, name, ha="right", va="center", fontsize=9,
                weight="bold")
        for c, state in enumerate(cells):
            ax.add_patch(Rectangle(
                (c * 1.9, r - 0.33), 1.75, 0.66,
                facecolor=color[state], alpha=0.8, edgecolor=EDGE,
                lw=0.6,
            ))
            ax.text(c * 1.9 + 0.875, r, state, ha="center",
                    va="center", color="white", fontsize=8.5)
        ax.text(3 * 1.9 + 0.25, r, note, va="center", fontsize=8,
                color="#444")
    for c, col in enumerate(cols):
        ax.text(c * 1.9 + 0.875, -0.75, col, ha="center", fontsize=9,
                weight="bold")
    ax.set_xlim(-2.6, 12.4)
    ax.set_ylim(3.6, -1.1)
    ax.axis("off")
    ax.set_title("FSDP sharding modes (per-chip view of each tensor "
                 "group)", fontsize=10, loc="left")
    _save(fig, "fsdp_modes.svg")


def tp_col_row(T=2):
    """Megatron column->row parallel MLP: which matmul shards how,
    and where the one psum lands (tp.py llama/mlp rules)."""
    fig, ax = plt.subplots(figsize=(9.6, 3.2))

    def block(x, y, w, h, color, label, alpha=0.85, fs=8.5):
        ax.add_patch(Rectangle((x, y), w, h, facecolor=color,
                               alpha=alpha, edgecolor=EDGE, lw=0.7))
        ax.text(x + w / 2, y + h / 2, label, ha="center", va="center",
                fontsize=fs, color="white")

    # X (replicated)
    block(0, 0.4, 1.2, 1.2, "#999999", "X\n[B,D]")
    ax.text(1.55, 1.0, "@", fontsize=13, va="center")
    # A column-split
    for t in range(T):
        block(1.9 + t * 0.75, 0.4, 0.7, 1.2, MB_COLORS[t],
              f"A{t}\n[D,F/{T}]")
    ax.text(1.9 + T * 0.75 + 0.15, 1.0, "=", fontsize=13, va="center")
    for t in range(T):
        block(3.8 + t * 0.75, 0.4, 0.7, 1.2, MB_COLORS[t],
              f"Y{t}")
    ax.text(4.6, 2.0, "column-parallel: activations stay sharded,\n"
            "gelu applies per shard, NO communication",
            fontsize=8.5, ha="center")
    ax.text(5.65, 1.0, "@", fontsize=13, va="center")
    # B row-split
    for t in range(T):
        block(5.95 + t * 0.75, 0.4, 0.7, 1.2, MB_COLORS[t],
              f"B{t}\n[F/{T},D]")
    ax.text(7.6, 1.0, "->", fontsize=13, va="center")
    block(8.1, 0.4, 1.3, 1.2, "#CC79A7", "psum\nover 'model'")
    ax.text(9.75, 1.0, "=", fontsize=13, va="center")
    block(10.05, 0.4, 1.2, 1.2, "#999999", "Z\n[B,D]")
    ax.text(8.75, 2.0, "row-parallel: partial products\nmeet in ONE "
            "all-reduce", fontsize=8.5, ha="center")
    ax.set_xlim(-0.3, 11.6)
    ax.set_ylim(-0.3, 2.8)
    ax.axis("off")
    ax.set_title(
        f"Tensor-parallel MLP across {T} chips: shard A by columns, "
        "B by rows -- one psum per block, riding the ICI ring",
        fontsize=10, loc="left",
    )
    _save(fig, "tp_col_row.svg")


def ulysses_all_to_all(C=4, H=8):
    """DeepSpeed-Ulysses head/sequence exchange: the all_to_all turns
    seq-sharded/all-heads into head-sharded/full-seq and back
    (sp_ulysses.py). Tiles are colored by the device that OWNED them
    before the exchange, so the shuffle is visible."""
    fig, axes = plt.subplots(1, 2, figsize=(10.2, 3.4),
                             gridspec_kw={"wspace": 0.35})
    hp = H // C  # heads per device after the exchange
    for ax, phase in ((axes[0], "before"), (axes[1], "after")):
        for d in range(C):          # device row
            for c in range(C):      # seq-chunk column
                for g in range(C):  # head-group sub-column
                    if phase == "before":
                        owner, visible = d, (c == d)
                    else:
                        owner, visible = c, (g == d)
                    x = c * (C + 0.6) + g
                    ax.add_patch(Rectangle(
                        (x, d * 1.3), 0.92, 1,
                        facecolor=MB_COLORS[owner % len(MB_COLORS)],
                        alpha=0.9 if visible else 0.12,
                        edgecolor=EDGE, lw=0.5,
                    ))
                    if visible:
                        ax.text(x + 0.46, d * 1.3 + 0.5,
                                f"s{c}\nh{g * hp}-{g * hp + hp - 1}",
                                ha="center", va="center", fontsize=5.6,
                                color="white")
            ax.text(-0.7, d * 1.3 + 0.5, f"d{d}", ha="right",
                    va="center", fontsize=9)
        ax.set_xlim(-1.6, C * (C + 0.6))
        ax.set_ylim(C * 1.3, -0.6)
        ax.axis("off")
        ax.set_title(
            "before: seq chunk s_d, ALL heads" if phase == "before"
            else f"after all_to_all: FULL seq, heads {hp}/device",
            fontsize=9.5, loc="left",
        )
    fig.suptitle(
        f"Ulysses sequence parallelism ({C} devices, {H} heads): one "
        "all_to_all scatters heads / gathers sequence before "
        "attention; the inverse follows it (sp_ulysses.py)",
        fontsize=10, x=0.01, ha="left",
    )
    _save(fig, "ulysses_all_to_all.svg")


def ring_attention_rotation(C=4):
    """Ring attention's KV rotation: C-1 ppermute hops; every device
    sees every KV block once, merging partials by LSE
    (ring_attention.py)."""
    fig, ax = plt.subplots(figsize=(9.6, 3.1))
    for step in range(C):
        x0 = step * (C * 0.62 + 1.5)
        for d in range(C):
            kv = (d - step) % C
            ax.add_patch(Rectangle(
                (x0 + d * 0.62, 0), 0.56, 0.9,
                facecolor=MB_COLORS[kv], alpha=0.9,
                edgecolor=EDGE, lw=0.6,
            ))
            ax.text(x0 + d * 0.62 + 0.28, 0.45, f"kv{kv}",
                    ha="center", va="center", fontsize=6.5,
                    color="white")
            ax.text(x0 + d * 0.62 + 0.28, -0.28, f"d{d}", ha="center",
                    fontsize=6.5, color="#555")
        ax.text(x0 + C * 0.31, 1.25,
                f"step {step}:\nattn(q_d, kv_{{d-{step}}})",
                ha="center", fontsize=7.5)
        if step < C - 1:
            ax.annotate(
                "", xy=(x0 + C * 0.62 + 1.1, 0.45),
                xytext=(x0 + C * 0.62 + 0.15, 0.45),
                arrowprops=dict(arrowstyle="->", color="#D55E00",
                                lw=1.6),
            )
            ax.text(x0 + C * 0.62 + 0.62, 0.72, "ppermute",
                    ha="center", fontsize=6.5, color="#D55E00")
    ax.set_xlim(-0.4, C * (C * 0.62 + 1.5))
    ax.set_ylim(-0.8, 2.1)
    ax.axis("off")
    ax.set_title(
        f"Ring attention ({C} devices): KV blocks rotate one hop per "
        "step; each device merges C partial attentions exactly via "
        "online-softmax LSE (lse_merge), overlapping the hop with "
        "compute", fontsize=10, loc="left",
    )
    _save(fig, "ring_attention.svg")


def fsdp_step_flow():
    """One FULL_SHARD training step as a comm/compute timeline
    (fsdp.py + the trainer's donated-state jit)."""
    fig, ax = plt.subplots(figsize=(10.4, 2.7))
    stages = [
        ("all-gather\nparams (bf16)", "#56B4E9", 1.5),
        ("forward\n(sharded batch)", "#0072B2", 2.4),
        ("all-gather\nparams (bf16)", "#56B4E9", 1.5),
        ("backward", "#0072B2", 2.9),
        ("reduce-scatter\ngrads (fp32)", "#CC79A7", 1.7),
        ("AdamW on\nLOCAL shard", "#009E73", 1.6),
    ]
    x = 0.0
    for label, color, w in stages:
        ax.add_patch(Rectangle((x, 0), w - 0.12, 1, facecolor=color,
                               alpha=0.88, edgecolor=EDGE, lw=0.7))
        ax.text(x + (w - 0.12) / 2, 0.5, label, ha="center",
                va="center", fontsize=8, color="white")
        x += w
    ax.annotate("", xy=(3.9, 1.45), xytext=(0.7, 1.45),
                arrowprops=dict(arrowstyle="->", color="#777", lw=1.1))
    ax.text(2.3, 1.62, "XLA prefetches the NEXT layer's gather under "
            "this layer's compute (latency-hiding scheduler)",
            ha="center", fontsize=7.5, color="#555")
    ax.text(x - 1.0, -0.42,
            "params/grads/opt state never exist whole on any chip",
            ha="right", fontsize=8, color="#444")
    ax.set_xlim(-0.2, x + 0.3)
    ax.set_ylim(-0.7, 2.0)
    ax.axis("off")
    ax.set_title(
        "FULL_SHARD step: per-layer bf16 gathers ride ICI, one fp32 "
        "reduce-scatter per step, optimizer touches only the local "
        "1/N shard", fontsize=10, loc="left",
    )
    _save(fig, "fsdp_step_flow.svg")


def multislice_mesh(nslices=2, nx=2, ny=2):
    """Multi-slice topology: ICI torus inside each slice, DCN between
    slices; the hybrid mesh maps model/data axes accordingly
    (runtime/mesh.py multi-slice MeshSpec)."""
    fig, ax = plt.subplots(figsize=(8.8, 4.2))
    gap = nx + 1.6
    for s in range(nslices):
        x_off = s * gap
        ax.add_patch(Rectangle(
            (x_off - 0.55, -0.55), nx - 1 + 1.1, ny - 1 + 1.1,
            facecolor="none", edgecolor="#999", lw=1.2, ls=":",
        ))
        ax.text(x_off + (nx - 1) / 2, ny - 1 + 0.75,
                f"slice {s} (ICI torus)", ha="center", fontsize=8.5,
                color="#666")
        for x in range(nx):
            for y in range(ny):
                ax.add_patch(Rectangle(
                    (x_off + x - 0.26, y - 0.26), 0.52, 0.52,
                    facecolor=MB_COLORS[s], alpha=0.88,
                    edgecolor=EDGE, zorder=3,
                ))
                if x + 1 < nx:
                    ax.plot([x_off + x + 0.26, x_off + x + 0.74],
                            [y, y], color="#999", lw=1.4)
                if y + 1 < ny:
                    ax.plot([x_off + x, x_off + x],
                            [y + 0.26, y + 0.74], color="#999", lw=1.4)
    for y in range(ny):
        ax.annotate(
            "", xy=(gap - 0.65, y), xytext=(nx - 1 + 0.35, y),
            arrowprops=dict(arrowstyle="<->", color="#D55E00", lw=1.5),
        )
    ax.text((gap + nx - 1) / 2 - 0.15, ny - 0.4, "DCN",
            ha="center", fontsize=9, color="#D55E00", weight="bold")
    ax.text(
        (gap + nx - 1) / 2 - 0.15, -1.05,
        'axes={"data": slices x ..., "model": intra-slice}:\n'
        "TP/SP collectives stay on ICI; only the per-step FSDP/DP "
        "gradient reduction crosses DCN",
        ha="center", fontsize=8.5, color="#444",
    )
    ax.set_xlim(-1.1, gap * nslices - 1.0)
    ax.set_ylim(-1.7, ny + 0.6)
    ax.set_aspect("equal")
    ax.axis("off")
    ax.set_title(
        "Multi-slice mesh: bandwidth-hungry axes inside the slice, "
        "bandwidth-tolerant axis across DCN (the reference's "
        "NVLink-intra / Slingshot-inter doctrine, TPU edition)",
        fontsize=10, loc="left",
    )
    _save(fig, "multislice_mesh.svg")


def hbm_memory():
    """Per-chip HBM during a 7B training step, by strategy -- computed
    from the framework's own fit analyzer (checks/fit.py analyze with
    do_compile=False: real param pytree via eval_shape, real sharding
    rules, the analytic activation model). The TPU edition of the
    reference's gpu_memory_components.png: instead of naming the
    components of one OOM, it shows how each strategy moves them."""
    import sys

    sys.path.insert(0, str(pathlib.Path(__file__).parent.parent))
    from tpu_hpc.checks.fit import analyze
    from tpu_hpc.models import llama2

    GIB = 1 << 30
    cfg = llama2.LlamaConfig(max_seq_len=4096, remat=True)
    n = llama2.count_params(cfg)
    chips, batch = 32, 64

    bars = []  # (label, params, grads, opt, act) in GiB per chip
    # Pure DP: every chip holds the whole model + opt state;
    # activations are batch-sharded exactly as under FSDP (same
    # analytic model, tp=1).
    from tpu_hpc.checks.fit import activation_model

    dp_act = sum(activation_model(
        cfg, dp=chips, tp_size=1, global_batch=batch, seq_len=4096
    ).values()) / GIB
    dp_statics = [4 * n / GIB, 4 * n / GIB, 8 * n / GIB]
    bars.append(("DP x32\n(replicated)", *dp_statics, dp_act))
    for label, kw in [
        ("FSDP x32", dict(dp=chips, tp_size=1)),
        ("hybrid 8x4\nFSDP x TP(+SP)", dict(dp=8, tp_size=4)),
        ("hybrid 8x4\n+ accum 8", dict(dp=8, tp_size=4, grad_accum=8)),
        ("hybrid 8x4\naccum 8, bf16 mom.",
         dict(dp=8, tp_size=4, grad_accum=8,
              moments_dtype="bfloat16")),
    ]:
        r = analyze(cfg, global_batch=batch, seq_len=4096,
                    do_compile=False, **kw)
        bars.append((
            label, r.param_bytes / GIB, r.grad_bytes / GIB,
            r.opt_bytes / GIB, sum(r.act_bytes.values()) / GIB,
        ))

    comp_colors = ["#0072B2", "#E69F00", "#CC79A7", "#009E73"]
    comp_names = ["params (fp32 master)", "grads",
                  "AdamW mu+nu", "activations"]
    fig, ax = plt.subplots(figsize=(9.2, 4.2))
    clip = 48  # GiB shown; the DP bar annotates its true height
    for i, (label, p, g, o, a) in enumerate(bars):
        y = 0.0
        total = p + g + o + (a or 0)
        for val, color in zip((p, g, o, a), comp_colors):
            if val is None:
                continue
            h = min(val, clip - y)
            if h <= 0:
                break
            ax.add_patch(Rectangle((i - 0.32, y), 0.64, h,
                                   facecolor=color, alpha=0.85,
                                   edgecolor=EDGE, lw=0.5))
            y += h
        note = f"{total:.1f} GiB"
        if total > clip:
            note += " (clipped)"
        ax.text(i, min(total, clip) + 1.1, note, ha="center",
                fontsize=8.5)
        ax.text(i, -2.6, label, ha="center", va="top", fontsize=8.5)
    for hbm, name in ((16, "v5e HBM 16 GiB"), (32, "v4 HBM 32 GiB")):
        ax.axhline(hbm, color="#D55E00", lw=1.1, ls="--", alpha=0.8)
        ax.text(len(bars) - 0.45, hbm + 0.5, name, fontsize=8,
                color="#D55E00", ha="right")
    handles = [Rectangle((0, 0), 1, 1, facecolor=c, alpha=0.85)
               for c in comp_colors]
    ax.legend(handles, comp_names, loc="upper right", fontsize=8,
              framealpha=0.9)
    ax.set_xlim(-0.7, len(bars) - 0.3)
    ax.set_ylim(0, clip + 4)
    ax.set_xticks([])
    ax.set_ylabel("GiB per chip", fontsize=9)
    ax.set_title(
        f"Where a 7B training step's HBM goes ({chips} chips, batch "
        f"{batch} x 4096) -- from checks/fit.py's accounting",
        fontsize=10, loc="left",
    )
    _save(fig, "hbm_memory.svg")


def parallelism_modes():
    """Six-panel overview: what each strategy splits across 4 chips.
    The TPU edition of the reference's modes_of_parallelism /
    data-vs-model-parallelism overview figures."""
    fig, axes = plt.subplots(2, 3, figsize=(10.2, 6.0),
                             gridspec_kw={"wspace": 0.25,
                                          "hspace": 0.45})

    def chipframe(ax, title, sub):
        ax.set_xlim(-0.2, 4.2)
        ax.set_ylim(-1.4, 4.4)
        ax.axis("off")
        ax.set_title(title, fontsize=9.5, loc="left")
        ax.text(2.0, -1.15, sub, ha="center", fontsize=7.8,
                color="#444")

    def grid(ax, split, labels):
        """A 4x4 'tensor' split along rows/cols/blocks, one color per
        owning chip."""
        for i in range(4):
            for j in range(4):
                if split == "rows":
                    owner = i
                elif split == "cols":
                    owner = j
                elif split == "blocks":
                    owner = (i // 2) * 2 + (j // 2)
                else:
                    owner = -1  # replicated
                color = (MB_COLORS[owner % len(MB_COLORS)]
                         if owner >= 0 else "#bbbbbb")
                ax.add_patch(Rectangle((j, 3 - i), 1, 1,
                                       facecolor=color, alpha=0.8,
                                       edgecolor="white", lw=1.2))
        if labels:
            ax.text(-0.12, 2.0, labels[0], rotation=90, va="center",
                    ha="right", fontsize=8)
            ax.text(2.0, 4.12, labels[1], ha="center", fontsize=8)

    panels = [
        ("DP / FSDP: split the BATCH", "rows", ("batch", "features"),
         "each chip trains its own rows; FSDP also\nshards the "
         "params over the same axis"),
        ("TP: split the WEIGHTS", "cols", ("d_in", "d_out"),
         "column/row-parallel matmuls; one psum\nper block over the "
         "'model' axis"),
        ("PP: split the LAYERS", "rows", ("layers", ""),
         "stages own layer ranges; microbatches\nstream through "
         "ppermute hops"),
        ("SP / ring: split the SEQUENCE", "cols", ("", "sequence"),
         "each chip holds S/4 tokens; ring/all_to_all\nmoves KV or "
         "heads, never the stream"),
        ("Domain: split SPACE", "blocks", ("lat", "lon"),
         "2D tiles + halo exchange for conv\nstencils (weather grids)"),
        ("Hybrid: compose axes", "blocks", ("data", "model"),
         "mesh axes multiply: FSDP x TP x SP x PP\non one device mesh"),
    ]
    for ax, (title, split, labels, sub) in zip(axes.flat, panels):
        grid(ax, split, labels)
        chipframe(ax, title, sub)
    fig.suptitle(
        "What gets split: every parallelism mode is a sharding of "
        "some axis over the same chips", fontsize=10.5, x=0.5, y=0.99,
    )
    _save(fig, "parallelism_modes.svg")


def pp_measured_rows():
    """Round-5 measured single-chip pipeline rows vs the DP headline
    (one measure -> one hue; identity lives in the row labels)."""
    rows = [
        ("DP headline (512/1024 tiling)", 124.2),
        ("DP headline (512/512)", 121.4),
        ("GPipe + remat_stage", 103.1),
        ("1F1B (remat backward)", 97.6),
    ]
    bound = 121.4 * 3 / 4  # the naive 4/3-FLOPs remat bound
    fig, ax = plt.subplots(figsize=(6.4, 2.4))
    names = [r[0] for r in rows][::-1]
    vals = [r[1] for r in rows][::-1]
    bars = ax.barh(names, vals, height=0.62, color="#0072B2",
                   edgecolor="none")
    for b, v in zip(bars, vals):
        ax.text(v + 1.5, b.get_y() + b.get_height() / 2,
                f"{v:.1f}k", va="center", fontsize=8.5,
                color="#333333")
    ax.axvline(bound, color="#999999", lw=1.2, ls="--")
    ax.text(bound - 1.5, 3.45, "4/3-FLOPs bound (91.0k)",
            ha="right", fontsize=7.5, color="#666666")
    ax.set_xlim(0, 140)
    ax.set_xlabel("measured tokens/s/chip (thousands, v5e single chip)")
    ax.set_title(
        "Pipeline schedules vs the data-parallel headline (round 5)",
        fontsize=9.5,
    )
    for s in ("top", "right", "left"):
        ax.spines[s].set_visible(False)
    ax.tick_params(left=False)
    ax.xaxis.grid(True, color="#e6e6e6", lw=0.7)
    ax.set_axisbelow(True)
    _save(fig, "pp_measured_rows.svg")


if __name__ == "__main__":
    pipeline_schedules()
    mesh_torus()
    zigzag_ring()
    halo_exchange()
    fsdp_modes()
    tp_col_row()
    ulysses_all_to_all()
    ring_attention_rotation()
    fsdp_step_flow()
    multislice_mesh()
    hbm_memory()
    parallelism_modes()
    pp_measured_rows()
