#!/usr/bin/env bash
# Round-5 hardware watchdog: probe the TPU tunnel every 4 minutes;
# when it answers, drain the remaining job queue in priority order,
# banking each job's outputs into HW_QUEUE_r05/ as it completes (so a
# partial window still lands in the repo). Jobs already banked in the
# 08:27-08:51 UTC window: bench_headline (121,361 tok/s/chip, 56.3%
# MFU), bench_bk1024 (124,171, 57.6%), bench_pp_1f1b (97,573, 44.6%),
# bench_pp_gpipe (103,088, 47.2%).
#
# Start:  nohup setsid bash HW_QUEUE_r05/watchdog.sh \
#             > HW_QUEUE_r05/watchdog.log 2>&1 &
set -u
cd "$(dirname "$0")/.."
Q=HW_QUEUE_r05
DONEDIR="$Q/done"
mkdir -p "$DONEDIR"

probe() {
    timeout 180 python -c "import jax; d=jax.devices(); print('PROBE_OK', len(d))" 2>/dev/null | grep -q PROBE_OK
}

run_job() { # name cmd...
    local name="$1"; shift
    [ -e "$DONEDIR/$name" ] && return 0
    echo "[$(date -u +%H:%M:%S)] running $name: $*"
    if "$@" > "$Q/$name.log" 2>&1; then
        echo "[$(date -u +%H:%M:%S)] $name ok"
        touch "$DONEDIR/$name"
    else
        echo "[$(date -u +%H:%M:%S)] $name FAILED rc=$? (will retry next window)"
        return 1
    fi
}

while :; do
    if ! probe; then
        echo "[$(date -u +%H:%M:%S)] tunnel down; sleeping 240s"
        sleep 240
        continue
    fi
    echo "[$(date -u +%H:%M:%S)] tunnel UP; draining queue"
    export TPU_HPC_BENCH_NO_PROBE=1
    run_job pp_stash_mb2 python bench.py --workload llama-pp \
        --pp-schedule 1f1b --pp-backward stash --pp-microbatch-size 2
    run_job pp_interleaved python bench.py --workload llama-pp \
        --pp-schedule interleaved-1f1b
    run_job convergence_tpu python \
        examples/06_hybrid_parallelism/real_corpus_convergence.py \
        --dim 512 --layers 8 --heads 8 --seq-len 1024 \
        --global-batch-size 8 --epochs 5
    run_job comm_bench_chip python -m tpu_hpc.comm.bench \
        --output "$Q/comm_bench_chip.csv"
    run_job digits50k_resnet python \
        examples/02_fully_sharded_fsdp/train_resnet_fsdp.py \
        --dataset digits50k --depth 18 --strategy ddp \
        --global-batch-size 256 --steps-per-epoch 195 --epochs 8 \
        --log-file "$Q/digits50k_resnet.jsonl"
    run_job pp_llama_1f1b python bench.py --workload llama-pp \
        --pp-model llama --pp-schedule 1f1b
    run_job pp_llama_gpipe python bench.py --workload llama-pp \
        --pp-model llama --pp-schedule gpipe
    run_job pp_llama_stash python bench.py --workload llama-pp \
        --pp-model llama --pp-schedule 1f1b --pp-backward stash
    run_job headline_accum16 python bench.py --grad-accum-steps 16
    run_job bench_all python bench.py --all --out "$Q/BENCH_EXTRA_r05.md"
    if [ "$(ls "$DONEDIR" | wc -l)" -ge 10 ]; then
        echo "[$(date -u +%H:%M:%S)] queue drained; exiting"
        exit 0
    fi
    sleep 120
done
